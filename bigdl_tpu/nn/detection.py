"""Object-detection ops (≙ nn/Anchor.scala, PriorBox.scala, Nms.scala,
Proposal.scala, RoiPooling.scala, DetectionOutputSSD.scala,
DetectionOutputFrcnn.scala).

Box decode / prior generation / RoI pooling are jittable jnp (static
shapes, mask-based bins — TPU-friendly).  Greedy NMS and the final
detection assembly are inference-time host post-processing with
data-dependent output sizes, exactly as in the reference (which runs them
on the JVM driver); they run in numpy on host.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .module import Module
from ..utils.table import Table, as_list


# --------------------------------------------------------------------- #
# prior / anchor generation                                             #
# --------------------------------------------------------------------- #
class PriorBox(Module):
    """SSD prior boxes for one feature map (nn/PriorBox.scala:44).

    forward(feature (N, C, H, W)) → (1, 2, H*W*numPriors*4): row 0 the
    normalized [xmin,ymin,xmax,ymax] priors (caffe order: per cell, per
    min_size: min box, sqrt(min*max) box, aspect-ratio boxes), row 1 the
    variances.  Computed with numpy at trace time (all-static geometry),
    returned as a jnp constant.
    """

    def __init__(self, min_sizes, max_sizes=None, aspect_ratios=None,
                 is_flip=True, is_clip=False, variances=None, offset=0.5,
                 img_h=0, img_w=0, img_size=0, step_h=0.0, step_w=0.0,
                 step=0.0, name=None):
        super().__init__(name=name)
        self.min_sizes = list(min_sizes)
        self.max_sizes = list(max_sizes) if max_sizes else []
        ars = [1.0]
        for ar in (aspect_ratios or []):
            if not any(abs(ar - a) < 1e-6 for a in ars):
                ars.append(float(ar))
                if is_flip:
                    ars.append(1.0 / float(ar))
        self.aspect_ratios = ars
        self.is_clip = is_clip
        self.variances = list(variances) if variances else [0.1]
        if len(self.variances) not in (1, 4):
            raise ValueError("must provide 1 or 4 variances")
        self.offset = offset
        if img_h and img_w:
            self.img_h, self.img_w = img_h, img_w
        else:
            self.img_h = self.img_w = img_size
        if step_h and step_w:
            self.step_h, self.step_w = step_h, step_w
        else:
            self.step_h = self.step_w = step
        self.num_priors = (len(self.aspect_ratios) * len(self.min_sizes)
                           + len(self.max_sizes))

    def _priors(self, layer_h, layer_w, img_h, img_w):
        step_h = self.step_h or img_h / layer_h
        step_w = self.step_w or img_w / layer_w
        boxes = []
        for h in range(layer_h):
            for w in range(layer_w):
                cx = (w + self.offset) * step_w
                cy = (h + self.offset) * step_h
                for i, mn in enumerate(self.min_sizes):
                    bw = bh = mn
                    boxes.append((cx, cy, bw, bh))
                    if self.max_sizes:
                        mx = self.max_sizes[i]
                        s = float(np.sqrt(mn * mx))
                        boxes.append((cx, cy, s, s))
                    for ar in self.aspect_ratios:
                        if abs(ar - 1.0) < 1e-6:
                            continue
                        r = float(np.sqrt(ar))
                        boxes.append((cx, cy, bw * r, bh / r))
        b = np.asarray(boxes, np.float32)
        out = np.stack([(b[:, 0] - b[:, 2] / 2) / img_w,
                        (b[:, 1] - b[:, 3] / 2) / img_h,
                        (b[:, 0] + b[:, 2] / 2) / img_w,
                        (b[:, 1] + b[:, 3] / 2) / img_h], axis=1)
        if self.is_clip:
            out = np.clip(out, 0.0, 1.0)
        return out.reshape(-1)

    def apply(self, params, x, ctx):
        feat = as_list(x)[0] if isinstance(x, (Table, list, tuple)) else x
        layer_h, layer_w = int(feat.shape[2]), int(feat.shape[3])
        img_h = self.img_h or layer_h
        img_w = self.img_w or layer_w
        priors = self._priors(layer_h, layer_w, img_h, img_w)
        var = np.tile(np.asarray(
            self.variances if len(self.variances) == 4
            else self.variances * 4, np.float32), priors.size // 4)
        out = np.stack([priors, var])[None]
        return jnp.asarray(out)


class Anchor:
    """RPN anchor generation (nn/Anchor.scala:29).  Not a Module in the
    reference either — a geometry utility used by Proposal."""

    def __init__(self, ratios, scales, base_size=16):
        self.ratios = np.asarray(ratios, np.float32)
        self.scales = np.asarray(scales, np.float32)
        self.base_size = base_size
        self.num = len(self.ratios) * len(self.scales)

    def base_anchors(self):
        """(A, 4) anchors centered on the (base_size-1)/2 reference box."""
        base = np.array([0, 0, self.base_size - 1, self.base_size - 1],
                        np.float32)
        w, h = base[2] - base[0] + 1, base[3] - base[1] + 1
        cx, cy = base[0] + 0.5 * (w - 1), base[1] + 0.5 * (h - 1)
        out = []
        size = w * h
        for r in self.ratios:
            ws = np.round(np.sqrt(size / r))
            hs = np.round(ws * r)
            for s in self.scales:
                W, H = ws * s, hs * s
                out.append([cx - 0.5 * (W - 1), cy - 0.5 * (H - 1),
                            cx + 0.5 * (W - 1), cy + 0.5 * (H - 1)])
        return np.asarray(out, np.float32)

    def generate_anchors(self, map_w, map_h, feat_stride=16.0):
        """All shifted anchors, shape (A*map_h*map_w, 4)."""
        base = self.base_anchors()
        sx = np.arange(map_w, dtype=np.float32) * feat_stride
        sy = np.arange(map_h, dtype=np.float32) * feat_stride
        gx, gy = np.meshgrid(sx, sy)
        shifts = np.stack([gx.ravel(), gy.ravel(),
                           gx.ravel(), gy.ravel()], axis=1)
        return (shifts[:, None, :] + base[None]).reshape(-1, 4)


# --------------------------------------------------------------------- #
# box decode + NMS                                                      #
# --------------------------------------------------------------------- #
def bbox_transform_inv(boxes, deltas):
    """Apply (dx,dy,dw,dh) regression deltas to [x1,y1,x2,y2] boxes."""
    boxes = jnp.asarray(boxes)
    widths = boxes[:, 2] - boxes[:, 0] + 1.0
    heights = boxes[:, 3] - boxes[:, 1] + 1.0
    cx = boxes[:, 0] + 0.5 * widths
    cy = boxes[:, 1] + 0.5 * heights
    dx, dy, dw, dh = deltas[:, 0], deltas[:, 1], deltas[:, 2], deltas[:, 3]
    pcx = dx * widths + cx
    pcy = dy * heights + cy
    pw = jnp.exp(dw) * widths
    ph = jnp.exp(dh) * heights
    return jnp.stack([pcx - 0.5 * pw, pcy - 0.5 * ph,
                      pcx + 0.5 * pw, pcy + 0.5 * ph], axis=1)


def clip_boxes(boxes, im_h, im_w):
    return jnp.stack([jnp.clip(boxes[:, 0], 0, im_w - 1.0),
                      jnp.clip(boxes[:, 1], 0, im_h - 1.0),
                      jnp.clip(boxes[:, 2], 0, im_w - 1.0),
                      jnp.clip(boxes[:, 3], 0, im_h - 1.0)], axis=1)


class Nms:
    """Greedy IoU NMS (nn/Nms.scala).  Host-side numpy, like the
    reference's JVM loop — called from inference post-processing only."""

    def nms(self, scores, boxes, thresh, max_num=-1, normalized=False):
        scores = np.asarray(scores)
        boxes = np.asarray(boxes)
        offset = 0.0 if normalized else 1.0
        x1, y1, x2, y2 = boxes.T
        areas = (x2 - x1 + offset) * (y2 - y1 + offset)
        order = scores.argsort()[::-1]
        keep = []
        while order.size:
            i = order[0]
            keep.append(int(i))
            if 0 < max_num <= len(keep):
                break
            xx1 = np.maximum(x1[i], x1[order[1:]])
            yy1 = np.maximum(y1[i], y1[order[1:]])
            xx2 = np.minimum(x2[i], x2[order[1:]])
            yy2 = np.minimum(y2[i], y2[order[1:]])
            w = np.maximum(0.0, xx2 - xx1 + offset)
            h = np.maximum(0.0, yy2 - yy1 + offset)
            inter = w * h
            iou = inter / (areas[i] + areas[order[1:]] - inter)
            order = order[1:][iou <= thresh]
        return keep


class Proposal(Module):
    """RPN proposal layer (nn/Proposal.scala:37).

    forward(Table(cls_scores (1, 2A, H, W), bbox_deltas (1, 4A, H, W),
    im_info [h, w, scale...])) → (postNmsTopN', 5) rows of
    [0, x1, y1, x2, y2].  Decode is jnp; ranking + NMS host-side.
    """

    def __init__(self, pre_nms_topn, post_nms_topn, ratios, scales,
                 rpn_min_size=16, feat_stride=16, nms_thresh=0.7, name=None):
        super().__init__(name=name)
        self.pre_nms_topn = pre_nms_topn
        self.post_nms_topn = post_nms_topn
        self.anchor = Anchor(ratios, scales)
        self.rpn_min_size = rpn_min_size
        self.feat_stride = feat_stride
        self.nms_thresh = nms_thresh
        self._nms = Nms()

    def apply(self, params, x, ctx):
        scores_map, deltas_map, im_info = as_list(x)[:3]
        im_info = np.asarray(im_info).reshape(-1)
        A = self.anchor.num
        H, W = int(scores_map.shape[2]), int(scores_map.shape[3])
        anchors = self.anchor.generate_anchors(W, H, self.feat_stride)
        # scores: second A channels are the "object" scores (caffe order)
        scores = np.asarray(scores_map)[0, A:].transpose(1, 2, 0).reshape(-1)
        deltas = np.asarray(deltas_map)[0].reshape(A, 4, H, W) \
            .transpose(2, 3, 0, 1).reshape(-1, 4)
        proposals = np.asarray(clip_boxes(
            bbox_transform_inv(anchors, jnp.asarray(deltas)),
            float(im_info[0]), float(im_info[1])))
        min_size = self.rpn_min_size * (im_info[2] if im_info.size > 2
                                        else 1.0)
        ws = proposals[:, 2] - proposals[:, 0] + 1
        hs = proposals[:, 3] - proposals[:, 1] + 1
        valid = np.where((ws >= min_size) & (hs >= min_size))[0]
        proposals, scores = proposals[valid], scores[valid]
        order = scores.argsort()[::-1][:self.pre_nms_topn]
        proposals, scores = proposals[order], scores[order]
        keep = self._nms.nms(scores, proposals, self.nms_thresh,
                             max_num=self.post_nms_topn)
        out = np.zeros((len(keep), 5), np.float32)
        out[:, 1:] = proposals[keep]
        return jnp.asarray(out)


class RoiPooling(Module):
    """RoI max pooling (nn/RoiPooling.scala:45).

    forward(Table(features (B, C, H, W), rois (N, 5) [batch_ix, x1, y1, x2,
    y2])) → (N, C, pooled_h, pooled_w).  Mask-based bin max — static
    shapes, vectorized over rois and bins, fully jittable.
    """

    def __init__(self, pooled_w, pooled_h, spatial_scale=1.0, name=None):
        super().__init__(name=name)
        self.pooled_w = pooled_w
        self.pooled_h = pooled_h
        self.spatial_scale = spatial_scale

    def apply(self, params, x, ctx):
        feats, rois = as_list(x)[:2]
        B, C, H, W = feats.shape
        rois = jnp.asarray(rois)
        batch_ix = rois[:, 0].astype(jnp.int32)
        boxes = jnp.round(rois[:, 1:] * self.spatial_scale)
        x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
        roi_w = jnp.maximum(x2 - x1 + 1.0, 1.0)
        roi_h = jnp.maximum(y2 - y1 + 1.0, 1.0)
        bin_w = roi_w / self.pooled_w
        bin_h = roi_h / self.pooled_h
        rows = jnp.arange(H, dtype=jnp.float32)
        cols = jnp.arange(W, dtype=jnp.float32)

        ph = jnp.arange(self.pooled_h, dtype=jnp.float32)
        pw = jnp.arange(self.pooled_w, dtype=jnp.float32)
        # bin edges per roi per bin: (N, ph) / (N, pw)
        hstart = jnp.floor(ph[None] * bin_h[:, None]) + y1[:, None]
        hend = jnp.ceil((ph[None] + 1) * bin_h[:, None]) + y1[:, None]
        wstart = jnp.floor(pw[None] * bin_w[:, None]) + x1[:, None]
        wend = jnp.ceil((pw[None] + 1) * bin_w[:, None]) + x1[:, None]
        # membership masks: (N, ph, H), (N, pw, W)
        rmask = ((rows[None, None] >= jnp.clip(hstart, 0, H)[..., None])
                 & (rows[None, None] < jnp.clip(hend, 0, H)[..., None]))
        cmask = ((cols[None, None] >= jnp.clip(wstart, 0, W)[..., None])
                 & (cols[None, None] < jnp.clip(wend, 0, W)[..., None]))
        roi_feats = feats[batch_ix]                      # (N, C, H, W)
        neg = jnp.finfo(feats.dtype).min
        # max is separable: reduce H with rmask, then W with cmask — peak
        # memory (N, C, ph, H, W) → (N, C, ph, W), never the joint
        # (..., ph, pw, H, W) product
        vals_h = jnp.where(rmask[:, None, :, :, None],
                           roi_feats[:, :, None], neg)   # (N,C,ph,H,W)
        red_h = jnp.max(vals_h, axis=3)                  # (N,C,ph,W)
        vals_w = jnp.where(cmask[:, None, None, :, :],
                           red_h[:, :, :, None], neg)    # (N,C,ph,pw,W)
        out = jnp.max(vals_w, axis=4)                    # (N,C,ph,pw)
        # empty bins pool to 0 (reference memsets to 0)
        empty = ~(jnp.any(rmask, axis=2)[:, :, None]
                  & jnp.any(cmask, axis=2)[:, None, :])  # (N,ph,pw)
        return jnp.where(empty[:, None], 0.0, out)


class DetectionOutputSSD(Module):
    """SSD detection assembly (nn/DetectionOutputSSD.scala:47): decode locs
    against priors, per-class score filter + NMS, keep top-k.  Host-side
    post-processing (variable-length output), like the reference.

    forward(Table(loc (N, nPriors*4), conf (N, nPriors*nClasses),
    priors (1, 2, nPriors*4))) → (N, keep) rows
    [batch_ix, class, score, x1, y1, x2, y2] as a single (M, 7) array.
    """

    def __init__(self, n_classes=21, share_location=True, bg_label=0,
                 nms_thresh=0.45, nms_topk=400, keep_top_k=200,
                 conf_thresh=0.01, variance_encoded_in_target=False,
                 name=None):
        super().__init__(name=name)
        self.n_classes = n_classes
        self.share_location = share_location
        self.bg_label = bg_label
        self.nms_thresh = nms_thresh
        self.nms_topk = nms_topk
        self.keep_top_k = keep_top_k
        self.conf_thresh = conf_thresh
        self.variance_encoded = variance_encoded_in_target
        self._nms = Nms()

    def _decode(self, loc, priors, variances):
        pcx = (priors[:, 0] + priors[:, 2]) / 2
        pcy = (priors[:, 1] + priors[:, 3]) / 2
        pw = priors[:, 2] - priors[:, 0]
        ph = priors[:, 3] - priors[:, 1]
        if self.variance_encoded:
            variances = np.ones_like(variances)
        cx = variances[:, 0] * loc[:, 0] * pw + pcx
        cy = variances[:, 1] * loc[:, 1] * ph + pcy
        w = np.exp(variances[:, 2] * loc[:, 2]) * pw
        h = np.exp(variances[:, 3] * loc[:, 3]) * ph
        return np.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                        axis=1)

    def apply(self, params, x, ctx):
        loc, conf, priors = as_list(x)[:3]
        loc = np.asarray(loc)
        conf = np.asarray(conf)
        priors = np.asarray(priors)
        n = loc.shape[0]
        prior_boxes = priors[0, 0].reshape(-1, 4)
        prior_vars = priors[0, 1].reshape(-1, 4)
        n_priors = prior_boxes.shape[0]
        results = []
        for b in range(n):
            if self.share_location:
                decoded_all = self._decode(loc[b].reshape(n_priors, 4),
                                           prior_boxes, prior_vars)
            else:
                per_class = loc[b].reshape(n_priors, self.n_classes, 4)
            scores = conf[b].reshape(n_priors, self.n_classes)
            cand = []
            for c in range(self.n_classes):
                if c == self.bg_label:
                    continue
                decoded = (decoded_all if self.share_location
                           else self._decode(per_class[:, c], prior_boxes,
                                             prior_vars))
                cs = scores[:, c]
                sel = np.where(cs > self.conf_thresh)[0]
                if not sel.size:
                    continue
                order = cs[sel].argsort()[::-1][:self.nms_topk]
                sel = sel[order]
                keep = self._nms.nms(cs[sel], decoded[sel], self.nms_thresh,
                                     normalized=True)
                for k in keep:
                    i = sel[k]
                    cand.append([b, c, cs[i], *decoded[i]])
            cand.sort(key=lambda r: -r[2])
            results.extend(cand[:self.keep_top_k])
        if not results:
            return jnp.zeros((0, 7), jnp.float32)
        return jnp.asarray(np.asarray(results, np.float32))


class DetectionOutputFrcnn(Module):
    """Faster-RCNN detection assembly (nn/DetectionOutputFrcnn.scala:43):
    per-class bbox regression decode + NMS over RoIs.

    forward(Table(rois (R, 5), cls_prob (R, nClasses),
    bbox_pred (R, nClasses*4), im_info)) → (M, 7) rows
    [0, class, score, x1, y1, x2, y2].
    """

    def __init__(self, n_classes=21, bbox_vote=False, nms_thresh=0.3,
                 max_per_image=100, thresh=0.05, name=None):
        super().__init__(name=name)
        self.n_classes = n_classes
        self.nms_thresh = nms_thresh
        self.max_per_image = max_per_image
        self.thresh = thresh
        self._nms = Nms()

    def apply(self, params, x, ctx):
        rois, cls_prob, bbox_pred, im_info = as_list(x)[:4]
        rois = np.asarray(rois)
        scores = np.asarray(cls_prob)
        deltas = np.asarray(bbox_pred)
        im_info = np.asarray(im_info).reshape(-1)
        boxes = rois[:, 1:5]
        results = []
        for c in range(1, self.n_classes):
            cls_deltas = deltas[:, c * 4:(c + 1) * 4]
            pred = np.asarray(clip_boxes(
                bbox_transform_inv(jnp.asarray(boxes),
                                   jnp.asarray(cls_deltas)),
                float(im_info[0]), float(im_info[1])))
            cs = scores[:, c]
            sel = np.where(cs > self.thresh)[0]
            if not sel.size:
                continue
            keep = self._nms.nms(cs[sel], pred[sel], self.nms_thresh)
            for k in keep:
                i = sel[k]
                results.append([0, c, cs[i], *pred[i]])
        results.sort(key=lambda r: -r[2])
        results = results[:self.max_per_image]
        if not results:
            return jnp.zeros((0, 7), jnp.float32)
        return jnp.asarray(np.asarray(results, np.float32))
