"""Sparse-input layers (≙ nn/SparseLinear.scala, LookupTableSparse.scala,
SparseJoinTable.scala).

XLA has no sparse tensor type, so sparse activities are
:class:`bigdl_tpu.tensor.SparseTensor` COO pytrees; every op here lowers to
gathers + ``segment_sum``, which vectorize cleanly on TPU for a fixed nnz.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .module import Module
from .init import Xavier, Zeros, init_tensor
from ..tensor import (SparseTensor, sparse_dense_matmul, embedding_bag,
                      sparse_concat)
from ..utils.table import Table, as_list


class SparseLinear(Module):
    """Linear over a 2-D SparseTensor input (nn/SparseLinear.scala:44).

    backward_start/backward_length mirror the reference's restricted
    grad-input window (1-based column range); gradients w.r.t. the sparse
    input are only defined for that dense sub-range.
    """

    def __init__(self, input_size, output_size, backward_start=-1,
                 backward_length=-1, with_bias=True, w_regularizer=None,
                 b_regularizer=None, name=None):
        super().__init__(name=name)
        self.input_size = input_size
        self.output_size = output_size
        self.backward_start = backward_start
        self.backward_length = backward_length
        self.with_bias = with_bias
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        p = {"weight": init_tensor(self, k1,
                                   (self.input_size, self.output_size),
                                   self.input_size, self.output_size,
                                   Xavier())}
        if self.with_bias:
            p["bias"] = init_tensor(self, k2, (self.output_size,),
                                    self.input_size, self.output_size,
                                    Zeros(), kind="bias")
        return {self.name: p}

    def apply(self, params, x, ctx):
        p = self.own(params)
        if not isinstance(x, SparseTensor):
            raise TypeError("SparseLinear input must be a SparseTensor")
        y = sparse_dense_matmul(x, p["weight"])
        if self.with_bias:
            y = y + p["bias"]
        return y


class LookupTableSparse(Module):
    """Embedding-bag over sparse ids (nn/LookupTableSparse.scala:44).

    Input: a 2-D SparseTensor of ids (batch, maxlen), or Table(ids, weights)
    with matching sparsity.  Ids are 1-based.  combiner ∈ {sum, mean, sqrtn};
    max_norm l2-renormalizes each embedding before combining.  One gather +
    one segment_sum per batch — the TPU shape of the reference's per-row
    loop.
    """

    def __init__(self, n_index, n_output, combiner="sum", max_norm=-1.0,
                 w_regularizer=None, name=None):
        super().__init__(name=name)
        if combiner not in ("sum", "mean", "sqrtn"):
            raise ValueError(f"combiner must be sum|mean|sqrtn: {combiner}")
        self.n_index = n_index
        self.n_output = n_output
        self.combiner = combiner
        self.max_norm = max_norm
        self.w_regularizer = w_regularizer

    def init(self, rng):
        w = init_tensor(self, rng, (self.n_index, self.n_output),
                        self.n_index, self.n_output, Xavier())
        return {self.name: {"weight": w}}

    def apply(self, params, x, ctx):
        w = self.own(params)["weight"]
        if isinstance(x, (Table, list, tuple)):
            ids_sp, weights_sp = as_list(x)[:2]
            weights = weights_sp.values
        else:
            ids_sp, weights = x, None
        if not isinstance(ids_sp, SparseTensor):
            raise TypeError("LookupTableSparse input must be a SparseTensor")
        return embedding_bag(w, ids_sp, per_id_weights=weights,
                             combiner=self.combiner, max_norm=self.max_norm)


class SparseJoinTable(Module):
    """Concatenate 2-D SparseTensors along `dimension` (1-based)
    (nn/SparseJoinTable.scala); only dim 2 (columns) is meaningful for
    batched sparse activities, matching the reference."""

    def __init__(self, dimension=2, name=None):
        super().__init__(name=name)
        self.dimension = dimension

    def apply(self, params, x, ctx):
        return sparse_concat(as_list(x), dim=self.dimension)
