"""Table (multi-activity) arithmetic and routing layers.

Reference files: nn/CAddTable.scala, CSubTable.scala, CMulTable.scala,
CDivTable.scala, CMaxTable.scala, CMinTable.scala, CAveTable.scala,
JoinTable.scala, SplitTable.scala, NarrowTable.scala, SelectTable.scala,
FlattenTable.scala, MixtureTable.scala, DotProduct.scala, MM.scala, MV.scala,
CosineDistance.scala, PairwiseDistance.scala, CrossProduct.scala,
BifurcateSplitTable.scala, DotProductCriterion lives in criterion.py.
"""
from __future__ import annotations

from functools import reduce

import jax
import jax.numpy as jnp

from .module import Module
from ..utils.table import Table, as_list


class CAddTable(Module):
    """Elementwise sum of a table of tensors (nn/CAddTable.scala)."""
    def __init__(self, inplace=False, name=None):
        super().__init__(name=name)

    def apply(self, params, x, ctx):
        return reduce(jnp.add, as_list(x))


class CSubTable(Module):
    """table[0] - table[1] (nn/CSubTable.scala)."""
    def apply(self, params, x, ctx):
        a, b = as_list(x)
        return a - b


class CMulTable(Module):
    """Elementwise product of a table of tensors (nn/CMulTable.scala)."""
    def apply(self, params, x, ctx):
        return reduce(jnp.multiply, as_list(x))


class CDivTable(Module):
    """table[0] / table[1] (nn/CDivTable.scala)."""
    def apply(self, params, x, ctx):
        a, b = as_list(x)
        return a / b


class CMaxTable(Module):
    """Elementwise max over a table of tensors (nn/CMaxTable.scala)."""
    def apply(self, params, x, ctx):
        return reduce(jnp.maximum, as_list(x))


class CMinTable(Module):
    """Elementwise min over a table of tensors (nn/CMinTable.scala)."""
    def apply(self, params, x, ctx):
        return reduce(jnp.minimum, as_list(x))


class CAveTable(Module):
    """Elementwise mean over a table of tensors (nn/CAveTable.scala)."""
    def __init__(self, inplace=False, name=None):
        super().__init__(name=name)

    def apply(self, params, x, ctx):
        xs = as_list(x)
        return reduce(jnp.add, xs) / float(len(xs))


class JoinTable(Module):
    """Concat table elements along 1-based `dimension`; n_input_dims allows
    batch offset like the reference (nn/JoinTable.scala)."""

    def __init__(self, dimension, n_input_dims=-1, name=None):
        super().__init__(name=name)
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def apply(self, params, x, ctx):
        xs = as_list(x)
        offset = 1 if (self.n_input_dims > 0
                       and xs[0].ndim > self.n_input_dims) else 0
        return jnp.concatenate(xs, axis=self.dimension - 1 + offset)


class SplitTable(Module):
    """Split a tensor along `dimension` into a table of slices
    (nn/SplitTable.scala)."""

    def __init__(self, dimension, n_input_dims=-1, name=None):
        super().__init__(name=name)
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def apply(self, params, x, ctx):
        offset = 1 if (self.n_input_dims > 0
                       and x.ndim > self.n_input_dims) else 0
        ax = (self.dimension - 1 + offset) if self.dimension > 0 \
            else x.ndim + self.dimension
        n = x.shape[ax]
        return Table(*[jnp.take(x, i, axis=ax) for i in range(n)])


class BifurcateSplitTable(Module):
    """Split into two halves along dim (nn/BifurcateSplitTable.scala)."""

    def __init__(self, dimension, name=None):
        super().__init__(name=name)
        self.dimension = dimension

    def apply(self, params, x, ctx):
        ax = self.dimension - 1
        half = x.shape[ax] // 2
        a = jax.lax.slice_in_dim(x, 0, half, axis=ax)
        b = jax.lax.slice_in_dim(x, half, x.shape[ax], axis=ax)
        return Table(a, b)


class NarrowTable(Module):
    """Table slice [offset, offset+length) with 1-based offset
    (nn/NarrowTable.scala)."""

    def __init__(self, offset, length=1, name=None):
        super().__init__(name=name)
        self.offset = offset
        self.length = length

    def apply(self, params, x, ctx):
        xs = as_list(x)
        length = self.length if self.length > 0 else \
            len(xs) - self.offset + 1 + self.length + 1
        return Table(*xs[self.offset - 1:self.offset - 1 + length])


class SelectTable(Module):
    """Select the i-th (1-based) table element (nn/SelectTable.scala)."""

    def __init__(self, index, name=None):
        super().__init__(name=name)
        self.index = index

    def apply(self, params, x, ctx):
        xs = as_list(x)
        i = self.index if self.index > 0 else len(xs) + self.index + 1
        return xs[i - 1]


class FlattenTable(Module):
    """Flatten nested tables into one flat table (nn/FlattenTable.scala)."""

    def apply(self, params, x, ctx):
        out = []

        def rec(v):
            if isinstance(v, (Table, list, tuple)):
                for e in as_list(v):
                    rec(e)
            else:
                out.append(v)

        rec(x)
        return Table(*out)


class MixtureTable(Module):
    """Mixture-of-experts blend: input {gater (B,E), experts table}
    (nn/MixtureTable.scala)."""

    def __init__(self, dim=None, name=None):
        super().__init__(name=name)
        self.dim = dim

    def apply(self, params, x, ctx):
        gater, experts = as_list(x)
        experts = as_list(experts)
        stacked = jnp.stack(experts, axis=1)  # (B, E, ...)
        g = gater.reshape(gater.shape + (1,) * (stacked.ndim - gater.ndim))
        return jnp.sum(stacked * g, axis=1)


class DotProduct(Module):
    """Row-wise dot product of two inputs (nn/DotProduct.scala)."""

    def apply(self, params, x, ctx):
        a, b = as_list(x)
        return jnp.sum(a * b, axis=-1)


class MM(Module):
    """Batched matrix-matrix product with optional transposes (nn/MM.scala)."""

    def __init__(self, trans_a=False, trans_b=False, name=None):
        super().__init__(name=name)
        self.trans_a = trans_a
        self.trans_b = trans_b

    def apply(self, params, x, ctx):
        a, b = as_list(x)
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)


class MV(Module):
    """Batched matrix-vector product (nn/MV.scala)."""

    def __init__(self, trans=False, name=None):
        super().__init__(name=name)
        self.trans = trans

    def apply(self, params, x, ctx):
        m, v = as_list(x)
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...ij,...j->...i", m, v)


class CosineDistance(Module):
    """Cosine similarity of two row batches (nn/CosineDistance.scala)."""

    def apply(self, params, x, ctx):
        a, b = as_list(x)
        an = jnp.maximum(jnp.linalg.norm(a, axis=-1), 1e-12)
        bn = jnp.maximum(jnp.linalg.norm(b, axis=-1), 1e-12)
        return jnp.sum(a * b, axis=-1) / (an * bn)


class PairwiseDistance(Module):
    """Lp distance between paired rows (nn/PairwiseDistance.scala)."""

    def __init__(self, norm=2, name=None):
        super().__init__(name=name)
        self.norm = norm

    def apply(self, params, x, ctx):
        a, b = as_list(x)
        d = jnp.abs(a - b) ** self.norm
        return jnp.sum(d, axis=-1) ** (1.0 / self.norm)


class CrossProduct(Module):
    """Pairwise dot products between all pairs of table elements
    (nn/CrossProduct.scala)."""

    def __init__(self, num_tensor=0, embedding_size=0, name=None):
        super().__init__(name=name)

    def apply(self, params, x, ctx):
        xs = as_list(x)
        outs = []
        for i in range(len(xs)):
            for j in range(i + 1, len(xs)):
                outs.append(jnp.sum(xs[i] * xs[j], axis=-1, keepdims=True))
        return jnp.concatenate(outs, axis=-1)


class DenseToSparse(Module):
    """nn/DenseToSparse.scala — on TPU sparse activities are represented
    densely (XLA has no sparse tensors); this is a tagged identity so graphs
    importing it still run."""

    def apply(self, params, x, ctx):
        return x


class MaskedSelect(Module):
    """nn/MaskedSelect.scala — select elements of input[0] where the byte
    mask input[1] is nonzero.  The output length is data-dependent, so this
    op cannot live under jit (XLA needs static shapes); it executes eagerly
    on host, like the reference's driver-side use."""

    def apply(self, params, x, ctx):
        import numpy as np
        tensor, mask = as_list(x)[:2]
        t = np.asarray(tensor)
        m = np.asarray(mask).astype(bool)
        return jnp.asarray(t[m])
