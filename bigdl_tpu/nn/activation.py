"""Activation layers.

Reference files: nn/ReLU.scala, ReLU6.scala, Tanh.scala, Sigmoid.scala,
ELU.scala, LeakyReLU.scala, PReLU.scala, RReLU.scala, SReLU.scala,
SoftMax.scala, SoftMin.scala, LogSoftMax.scala, LogSigmoid.scala,
SoftPlus.scala, SoftSign.scala, HardTanh.scala, HardSigmoid.scala,
HardShrink.scala, SoftShrink.scala, TanhShrink.scala, Threshold.scala,
BinaryThreshold.scala, Clamp.scala.

All are elementwise; XLA fuses them into neighbouring matmul/conv kernels so
they are effectively free on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .module import Module
from .init import init_tensor, ConstInit


class ReLU(Module):
    """max(x, 0) (nn/ReLU.scala)."""
    def __init__(self, ip=False, name=None):
        super().__init__(name=name)

    def apply(self, params, x, ctx):
        return jnp.maximum(x, 0)


class ReLU6(Module):
    """min(max(x, 0), 6) (nn/ReLU6.scala)."""
    def apply(self, params, x, ctx):
        return jnp.clip(x, 0, 6)


class Tanh(Module):
    """tanh(x) (nn/Tanh.scala)."""
    def apply(self, params, x, ctx):
        return jnp.tanh(x)


class Sigmoid(Module):
    """1 / (1 + exp(-x)) (nn/Sigmoid.scala)."""
    def apply(self, params, x, ctx):
        return jax.nn.sigmoid(x)


class ELU(Module):
    """x if x > 0 else alpha*(exp(x)-1) (nn/ELU.scala)."""
    def __init__(self, alpha=1.0, inplace=False, name=None):
        super().__init__(name=name)
        self.alpha = alpha

    def apply(self, params, x, ctx):
        return jnp.where(x > 0, x, self.alpha * jnp.expm1(x))


class LeakyReLU(Module):
    """x if x >= 0 else negval*x (nn/LeakyReLU.scala)."""
    def __init__(self, negval=0.01, inplace=False, name=None):
        super().__init__(name=name)
        self.negval = negval

    def apply(self, params, x, ctx):
        return jnp.where(x >= 0, x, self.negval * x)


class PReLU(Module):
    """Learned negative slope; n_output_plane=0 means one shared parameter
    (nn/PReLU.scala)."""

    def __init__(self, n_output_plane=0, name=None):
        super().__init__(name=name)
        self.n_output_plane = n_output_plane

    def init(self, rng):
        n = max(self.n_output_plane, 1)
        w = init_tensor(self, rng, (n,), n, n, ConstInit(0.25))
        return {self.name: {"weight": w}}

    def apply(self, params, x, ctx):
        w = self.own(params)["weight"].astype(x.dtype)
        if self.n_output_plane == 0:
            a = w[0]
        else:
            # channel dim is axis 1 for (N,C,...) inputs, matching reference NCHW
            shape = [1] * x.ndim
            shape[1 if x.ndim > 1 else 0] = self.n_output_plane
            a = w.reshape(shape)
        return jnp.where(x >= 0, x, a * x)


class RReLU(Module):
    """Randomized leaky ReLU (nn/RReLU.scala): slope ~ U(lower, upper) in
    training, (lower+upper)/2 in eval."""

    def __init__(self, lower=1.0 / 8, upper=1.0 / 3, inplace=False, name=None):
        super().__init__(name=name)
        self.lower, self.upper = lower, upper

    def apply(self, params, x, ctx):
        if ctx.training:
            a = jax.random.uniform(ctx.rng(self), x.shape, x.dtype,
                                   self.lower, self.upper)
        else:
            a = (self.lower + self.upper) / 2.0
        return jnp.where(x >= 0, x, a * x)


class SReLU(Module):
    """S-shaped ReLU with 4 learned params per channel (nn/SReLU.scala)."""

    def __init__(self, shape, shared_axes=None, name=None):
        super().__init__(name=name)
        self.shape = tuple(shape)
        self.shared_axes = shared_axes

    def _param_shape(self):
        shape = list(self.shape)
        if self.shared_axes:
            for ax in self.shared_axes:
                shape[ax - 1] = 1
        return tuple(shape)

    def init(self, rng):
        s = self._param_shape()
        n = 1
        return {self.name: {
            "tleft": jnp.zeros(s, jnp.float32),
            "aleft": jnp.full(s, 1.0, jnp.float32),
            "tright": jnp.full(s, 1.0, jnp.float32),
            "aright": jnp.full(s, 1.0, jnp.float32),
        }}

    def apply(self, params, x, ctx):
        p = self.own(params)
        tl, al = p["tleft"].astype(x.dtype), p["aleft"].astype(x.dtype)
        tr, ar = p["tright"].astype(x.dtype), p["aright"].astype(x.dtype)
        y = jnp.where(x >= tr, tr + ar * (x - tr), x)
        return jnp.where(y <= tl, tl + al * (y - tl), y)


def _softmax_axis(ndim):
    """nn/SoftMax.scala:39 updateOutput: 1D/2D normalize the last dim;
    3D (C,H,W) and 4D (N,C,H,W) normalize the CHANNEL dim per spatial
    position (stride = H*W)."""
    if ndim == 3:
        return 0
    if ndim == 4:
        return 1
    return -1


class SoftMax(Module):
    """Softmax; channel-wise for spatial (3D/4D) input by default
    (nn/SoftMax.scala).  Pass ``axis`` to override — e.g. the keras
    softmax activation uses axis=-1 so batched (N, T, C) sequence
    outputs normalize per step, not reference-3D-style over dim 0."""

    def __init__(self, axis=None, name=None):
        super().__init__(name=name)
        self.axis = axis

    def apply(self, params, x, ctx):
        ax = self.axis if self.axis is not None else _softmax_axis(x.ndim)
        return jax.nn.softmax(x, axis=ax)


class SoftMin(Module):
    """softmax(-x) (nn/SoftMin.scala)."""

    def __init__(self, axis=None, name=None):
        super().__init__(name=name)
        self.axis = axis

    def apply(self, params, x, ctx):
        ax = self.axis if self.axis is not None else _softmax_axis(x.ndim)
        return jax.nn.softmax(-x, axis=ax)


class LogSoftMax(Module):
    """log softmax over the last dim (nn/LogSoftMax.scala); feeds ClassNLLCriterion."""
    def apply(self, params, x, ctx):
        return jax.nn.log_softmax(x, axis=-1)


class LogSigmoid(Module):
    """log(1 / (1 + exp(-x))) (nn/LogSigmoid.scala)."""
    def apply(self, params, x, ctx):
        return jax.nn.log_sigmoid(x)


class SoftPlus(Module):
    """log(1 + exp(beta*x))/beta (nn/SoftPlus.scala)."""
    def __init__(self, beta=1.0, name=None):
        super().__init__(name=name)
        self.beta = beta

    def apply(self, params, x, ctx):
        return jax.nn.softplus(self.beta * x) / self.beta


class SoftSign(Module):
    """x / (1 + |x|) (nn/SoftSign.scala)."""
    def apply(self, params, x, ctx):
        return x / (1.0 + jnp.abs(x))


class HardTanh(Module):
    """clip(x, min_value, max_value) (nn/HardTanh.scala)."""
    def __init__(self, min_value=-1.0, max_value=1.0, inplace=False, name=None):
        super().__init__(name=name)
        self.min_value, self.max_value = min_value, max_value

    def apply(self, params, x, ctx):
        return jnp.clip(x, self.min_value, self.max_value)


class Clamp(HardTanh):
    """nn/Clamp.scala — HardTanh with explicit bounds."""

    def __init__(self, min_value, max_value, name=None):
        super().__init__(min_value=float(min_value), max_value=float(max_value),
                         name=name)


class HardSigmoid(Module):
    """clip(0.2x + 0.5, 0, 1) (nn/HardSigmoid.scala)."""

    def apply(self, params, x, ctx):
        return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


class HardShrink(Module):
    """x where |x| > lambda else 0 (nn/HardShrink.scala)."""
    def __init__(self, lambd=0.5, name=None):
        super().__init__(name=name)
        self.lambd = lambd

    def apply(self, params, x, ctx):
        return jnp.where(jnp.abs(x) > self.lambd, x, 0.0)


class SoftShrink(Module):
    """x -+ lambda outside [-lambda, lambda], else 0 (nn/SoftShrink.scala)."""
    def __init__(self, lambd=0.5, name=None):
        super().__init__(name=name)
        self.lambd = lambd

    def apply(self, params, x, ctx):
        return jnp.where(x > self.lambd, x - self.lambd,
                         jnp.where(x < -self.lambd, x + self.lambd, 0.0))


class TanhShrink(Module):
    """x - tanh(x) (nn/TanhShrink.scala)."""
    def apply(self, params, x, ctx):
        return x - jnp.tanh(x)


class Threshold(Module):
    """x if x > th else value (nn/Threshold.scala)."""

    def __init__(self, th=1e-6, v=0.0, ip=False, name=None):
        super().__init__(name=name)
        self.th, self.v = th, v

    def apply(self, params, x, ctx):
        return jnp.where(x > self.th, x, self.v)


class BinaryThreshold(Module):
    """1 if x > th else 0 (nn/BinaryThreshold.scala)."""

    def __init__(self, th=1e-6, ip=False, name=None):
        super().__init__(name=name)
        self.th = th

    def apply(self, params, x, ctx):
        return (x > self.th).astype(x.dtype)


class GELU(Module):
    """TPU-era extra (used by the TransformerLM flagship)."""

    def apply(self, params, x, ctx):
        return jax.nn.gelu(x)


class SiLU(Module):
    """x * sigmoid(x) — TPU-era extra (used by modern FFN blocks)."""
    def apply(self, params, x, ctx):
        return jax.nn.silu(x)
