"""Convolution layers.

Reference files: nn/SpatialConvolution.scala, SpatialDilatedConvolution.scala,
SpatialFullConvolution.scala, SpatialSeparableConvolution.scala,
SpatialShareConvolution.scala, TemporalConvolution.scala,
VolumetricConvolution.scala, VolumetricFullConvolution.scala,
LocallyConnected1D.scala, LocallyConnected2D.scala, nn/ops/DepthwiseConv2D.scala.

The reference hand-codes im2col + MKL GEMM; here every conv is one
``lax.conv_general_dilated`` call, which XLA tiles directly onto the MXU
(bf16-friendly, fused with bias/activation neighbours).  Weight layout is
(out, in/groups, kh, kw) = OIHW, matching the reference's NCHW default.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .module import Module
from .init import Xavier, Zeros, RandomUniform, init_tensor


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _same_pad(in_size, stride, ksize, dilation=1):
    """TF/Keras SAME padding split (lo, hi) for one spatial dim."""
    eff_k = (ksize - 1) * dilation + 1
    out = -(-in_size // stride)
    pad = max(0, (out - 1) * stride + eff_k - in_size)
    return pad // 2, pad - pad // 2


class SpatialConvolution(Module):
    """2D convolution (nn/SpatialConvolution.scala).

    padW/padH = -1 selects SAME padding (reference convention); nGroup
    maps to feature_group_count.  `format` is 'NCHW' (default) or 'NHWC'.
    """

    def __init__(self, n_input_plane, n_output_plane, kernel_w, kernel_h,
                 stride_w=1, stride_h=1, pad_w=0, pad_h=0, n_group=1,
                 propagate_back=True, w_regularizer=None, b_regularizer=None,
                 with_bias=True, format="NCHW", name=None):
        super().__init__(name=name)
        if n_input_plane % n_group or n_output_plane % n_group:
            raise ValueError("channels must be multiples of n_group")
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.n_group = n_group
        self.with_bias = with_bias
        self.format = format
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        kh, kw = self.kernel
        fan_in = self.n_input_plane // self.n_group * kh * kw
        fan_out = self.n_output_plane // self.n_group * kh * kw
        w = init_tensor(self, k1,
                        (self.n_output_plane, self.n_input_plane // self.n_group,
                         kh, kw), fan_in, fan_out, Xavier())
        p = {"weight": w}
        if self.with_bias:
            p["bias"] = init_tensor(self, k2, (self.n_output_plane,),
                                    fan_in, fan_out, Zeros(), kind="bias")
        return {self.name: p}

    def _padding(self, x_spatial):
        pads = []
        for i, (p, k, s) in enumerate(zip(self.pad, self.kernel, self.stride)):
            if p == -1:
                pads.append(_same_pad(x_spatial[i], s, k))
            else:
                pads.append((p, p))
        return pads

    def apply(self, params, x, ctx):
        p = self.own(params)
        w = p["weight"].astype(x.dtype)
        dn = ("NCHW", "OIHW", "NCHW") if self.format == "NCHW" \
            else ("NHWC", "OIHW", "NHWC")
        spatial = x.shape[2:4] if self.format == "NCHW" else x.shape[1:3]
        pads = self._padding(spatial)
        stride = self.stride
        if (self.kernel == (1, 1) and max(stride) > 1
                and pads == [(0, 0), (0, 0)]):
            # A 1x1 strided conv only reads the strided sub-grid, so
            # slice first and convolve dense.  Identical forward math;
            # the input gradient becomes (pad-scatter of a dense 1x1
            # matmul) instead of an lhs-dilated conv that spends 3/4 of
            # its MXU FLOPs multiplying inserted zeros (the dominant
            # backward waste in v1-style ResNets, where every
            # downsampling conv is 1x1/2).
            sh, sw = stride
            x = (x[:, :, ::sh, ::sw] if self.format == "NCHW"
                 else x[:, ::sh, ::sw, :])
            stride = (1, 1)
        y = lax.conv_general_dilated(
            x, w, window_strides=stride, padding=pads,
            feature_group_count=self.n_group,
            dimension_numbers=dn)
        if self.with_bias:
            b = p["bias"].astype(x.dtype)
            y = y + (b[None, :, None, None] if self.format == "NCHW"
                     else b[None, None, None, :])
        return y


class SpatialShareConvolution(SpatialConvolution):
    """nn/SpatialShareConvolution.scala — a memory-sharing variant of conv in
    the reference; identical math, and on TPU XLA owns buffer reuse, so this
    is an alias."""


class SpaceToDepthConvolution(SpatialConvolution):
    """Stride-2 conv computed on a 2x2 space-to-depth rearranged input.

    Exact reparameterization of the parent conv (same parameter tensor,
    same output): the kernel is zero-padded to even size and regrouped to
    act on the (H/2, W/2, 4*C) space-to-depth input with stride 1.  For
    convs whose input channel count is far below the MXU's 128 lanes —
    the ImageNet stem's 7x7/2 on C=3 is the canonical case — this
    quadruples lane utilization (C=3 -> 12) and replaces the strided
    conv's dilated input-gradient with a dense one.  NHWC only; stride
    must be 2 in both dims.
    """

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        if self.format != "NHWC":
            raise ValueError("SpaceToDepthConvolution requires NHWC")
        if self.stride != (2, 2):
            raise ValueError("SpaceToDepthConvolution requires stride 2")
        if self.n_group != 1:
            raise ValueError("SpaceToDepthConvolution requires n_group=1")
        if -1 in self.pad:
            raise ValueError("SpaceToDepthConvolution does not support "
                             "SAME (-1) padding; pass explicit pads")

    def apply(self, params, x, ctx):
        p = self.own(params)
        w = p["weight"].astype(x.dtype)          # OIHW (O, C, kh, kw)
        O, C, kh, kw = w.shape
        ph, pw = self.pad
        B, H, W, _ = x.shape
        out_h = (H + 2 * ph - kh) // 2 + 1
        out_w = (W + 2 * pw - kw) // 2 + 1
        k2h, k2w = -(-kh // 2) * 2, -(-kw // 2) * 2   # kernel padded even
        # zero-pad kernel to (k2h, k2w), then regroup taps k = 2a + d
        # into a (k2h/2, k2w/2) kernel over (dh, dw, c) channels
        wp = jnp.pad(w, ((0, 0), (0, 0), (0, k2h - kh), (0, k2w - kw)))
        wp = wp.reshape(O, C, k2h // 2, 2, k2w // 2, 2)
        wp = wp.transpose(0, 3, 5, 1, 2, 4).reshape(O, 4 * C,
                                                    k2h // 2, k2w // 2)
        # pad (or trim) the input to the even extent that exactly covers
        # every tap of every output position: extra zeros hit zero kernel
        # taps; rows beyond the last tap are unread, so trimming is exact
        # (an even kernel on an odd extent needs one row FEWER than H+ph)
        need_h = 2 * (out_h + k2h // 2 - 1)
        need_w = 2 * (out_w + k2w // 2 - 1)
        xp = jnp.pad(x, ((0, 0), (ph, max(0, need_h - H - ph)),
                         (pw, max(0, need_w - W - pw)), (0, 0)))
        xp = xp[:, :need_h, :need_w, :]
        Hp, Wp = xp.shape[1], xp.shape[2]
        xs = xp.reshape(B, Hp // 2, 2, Wp // 2, 2, C)
        xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(B, Hp // 2, Wp // 2,
                                                    4 * C)
        y = lax.conv_general_dilated(
            xs, wp, window_strides=(1, 1), padding=[(0, 0), (0, 0)],
            dimension_numbers=("NHWC", "OIHW", "NHWC"))
        y = y[:, :out_h, :out_w, :]
        if self.with_bias:
            y = y + p["bias"].astype(x.dtype)[None, None, None, :]
        return y


class SpatialDilatedConvolution(Module):
    """nn/SpatialDilatedConvolution.scala."""

    def __init__(self, n_input_plane, n_output_plane, kw, kh, dw=1, dh=1,
                 pad_w=0, pad_h=0, dilation_w=1, dilation_h=1,
                 w_regularizer=None, b_regularizer=None, with_bias=True,
                 name=None):
        super().__init__(name=name)
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel = (kh, kw)
        self.stride = (dh, dw)
        self.pad = (pad_h, pad_w)
        self.dilation = (dilation_h, dilation_w)
        self.with_bias = with_bias
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        kh, kw = self.kernel
        fan_in = self.n_input_plane * kh * kw
        fan_out = self.n_output_plane * kh * kw
        w = init_tensor(self, k1, (self.n_output_plane, self.n_input_plane,
                                   kh, kw), fan_in, fan_out, Xavier())
        p = {"weight": w}
        if self.with_bias:
            p["bias"] = init_tensor(self, k2, (self.n_output_plane,),
                                    fan_in, fan_out, Zeros(), kind="bias")
        return {self.name: p}

    def apply(self, params, x, ctx):
        p = self.own(params)
        pads = []
        for i, (pd, k, s) in enumerate(zip(self.pad, self.kernel, self.stride)):
            if pd == -1:
                pads.append(_same_pad(x.shape[2 + i], s, k, self.dilation[i]))
            else:
                pads.append((pd, pd))
        y = lax.conv_general_dilated(
            x, p["weight"].astype(x.dtype), window_strides=self.stride,
            padding=pads, rhs_dilation=self.dilation,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        if self.with_bias:
            y = y + p["bias"].astype(x.dtype)[None, :, None, None]
        return y


class SpatialFullConvolution(Module):
    """Transposed convolution (nn/SpatialFullConvolution.scala).

    Weight layout (in, out, kh, kw) as in the reference; adjW/adjH add to the
    output size.  Implemented as lhs-dilated conv (XLA's native transpose-conv
    form) rather than col2im.
    """

    def __init__(self, n_input_plane, n_output_plane, kw, kh, dw=1, dh=1,
                 pad_w=0, pad_h=0, adj_w=0, adj_h=0, n_group=1,
                 no_bias=False, w_regularizer=None, b_regularizer=None,
                 format="NCHW", name=None):
        super().__init__(name=name)
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel = (kh, kw)
        self.stride = (dh, dw)
        self.pad = (pad_h, pad_w)
        self.adj = (adj_h, adj_w)
        self.n_group = n_group
        self.with_bias = not no_bias
        self.format = format
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        kh, kw = self.kernel
        fan_in = self.n_input_plane // self.n_group * kh * kw
        fan_out = self.n_output_plane // self.n_group * kh * kw
        w = init_tensor(self, k1,
                        (self.n_input_plane, self.n_output_plane // self.n_group,
                         kh, kw), fan_in, fan_out, Xavier())
        p = {"weight": w}
        if self.with_bias:
            p["bias"] = init_tensor(self, k2, (self.n_output_plane,),
                                    fan_in, fan_out, Zeros(), kind="bias")
        return {self.name: p}

    def apply(self, params, x, ctx):
        p = self.own(params)
        w = p["weight"].astype(x.dtype)
        kh, kw = self.kernel
        sh, sw = self.stride
        ph, pw = self.pad
        ah, aw = self.adj
        g = self.n_group
        # out = (in-1)*stride - 2*pad + kernel + adj
        pads = [(kh - 1 - ph, kh - 1 - ph + ah), (kw - 1 - pw, kw - 1 - pw + aw)]
        # weight (I, O/g, kh, kw): flip spatially; for grouped conv XLA wants
        # the rhs I dim = in/g with output blocks per group, so regroup
        # (g, in/g, out/g, ...) -> (in/g, g*out/g, ...)
        w = w[:, :, ::-1, ::-1]
        if g > 1:
            i_g = self.n_input_plane // g
            o_g = self.n_output_plane // g
            w = (w.reshape(g, i_g, o_g, kh, kw)
                  .transpose(1, 0, 2, 3, 4)
                  .reshape(i_g, g * o_g, kh, kw))
        dn = ("NCHW", "IOHW", "NCHW") if self.format == "NCHW" \
            else ("NHWC", "IOHW", "NHWC")
        y = lax.conv_general_dilated(
            x, w, window_strides=(1, 1), padding=pads,
            lhs_dilation=(sh, sw), feature_group_count=g,
            dimension_numbers=dn)
        if self.with_bias:
            b = p["bias"].astype(x.dtype)
            y = y + (b[None, :, None, None] if self.format == "NCHW"
                     else b[None, None, None, :])
        return y


class SpatialSeparableConvolution(Module):
    """Depthwise conv followed by 1x1 pointwise conv
    (nn/SpatialSeparableConvolution.scala)."""

    def __init__(self, n_input_channel, n_output_channel, depth_multiplier,
                 kw, kh, sw=1, sh=1, pw=0, ph=0, with_bias=True,
                 data_format="NCHW", w_regularizer=None, b_regularizer=None,
                 p_regularizer=None, name=None):
        super().__init__(name=name)
        self.n_input_channel = n_input_channel
        self.n_output_channel = n_output_channel
        self.depth_multiplier = depth_multiplier
        self.kernel = (kh, kw)
        self.stride = (sh, sw)
        self.pad = (ph, pw)
        self.with_bias = with_bias
        self.format = data_format
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def init(self, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        kh, kw = self.kernel
        mid = self.n_input_channel * self.depth_multiplier
        fan_in = kh * kw
        dw = init_tensor(self, k1, (mid, 1, kh, kw), fan_in,
                         self.depth_multiplier * kh * kw, Xavier())
        pw_w = init_tensor(self, k2, (self.n_output_channel, mid, 1, 1),
                           mid, self.n_output_channel, Xavier())
        p = {"depth_weight": dw, "point_weight": pw_w}
        if self.with_bias:
            p["bias"] = init_tensor(self, k3, (self.n_output_channel,),
                                    mid, self.n_output_channel, Zeros(),
                                    kind="bias")
        return {self.name: p}

    def apply(self, params, x, ctx):
        p = self.own(params)
        if self.format == "NHWC":
            x = jnp.transpose(x, (0, 3, 1, 2))
        pads = []
        for i, (pd, k, s) in enumerate(zip(self.pad, self.kernel, self.stride)):
            pads.append(_same_pad(x.shape[2 + i], s, k) if pd == -1 else (pd, pd))
        y = lax.conv_general_dilated(
            x, p["depth_weight"].astype(x.dtype), window_strides=self.stride,
            padding=pads, feature_group_count=self.n_input_channel,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        y = lax.conv_general_dilated(
            y, p["point_weight"].astype(x.dtype), window_strides=(1, 1),
            padding=[(0, 0), (0, 0)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        if self.with_bias:
            y = y + p["bias"].astype(x.dtype)[None, :, None, None]
        if self.format == "NHWC":
            y = jnp.transpose(y, (0, 2, 3, 1))
        return y


class TemporalConvolution(Module):
    """1D convolution over (B, T, inputFrameSize) (nn/TemporalConvolution.scala)."""

    def __init__(self, input_frame_size, output_frame_size, kernel_w, stride_w=1,
                 propagate_back=True, w_regularizer=None, b_regularizer=None,
                 name=None):
        super().__init__(name=name)
        self.input_frame_size = input_frame_size
        self.output_frame_size = output_frame_size
        self.kernel_w = kernel_w
        self.stride_w = stride_w
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        fan_in = self.input_frame_size * self.kernel_w
        w = init_tensor(self, k1,
                        (self.output_frame_size, self.input_frame_size,
                         self.kernel_w),
                        fan_in, self.output_frame_size, Xavier())
        b = init_tensor(self, k2, (self.output_frame_size,), fan_in,
                        self.output_frame_size, Zeros(), kind="bias")
        return {self.name: {"weight": w, "bias": b}}

    def apply(self, params, x, ctx):
        p = self.own(params)
        # (B, T, C) -> NCW conv
        xt = jnp.swapaxes(x, 1, 2)
        y = lax.conv_general_dilated(
            xt, p["weight"].astype(x.dtype), window_strides=(self.stride_w,),
            padding=[(0, 0)], dimension_numbers=("NCH", "OIH", "NCH"))
        y = y + p["bias"].astype(x.dtype)[None, :, None]
        return jnp.swapaxes(y, 1, 2)


class VolumetricConvolution(Module):
    """3D convolution over (B, C, D, H, W) (nn/VolumetricConvolution.scala)."""

    def __init__(self, n_input_plane, n_output_plane, k_t, k_w, k_h,
                 d_t=1, d_w=1, d_h=1, pad_t=0, pad_w=0, pad_h=0,
                 with_bias=True, w_regularizer=None, b_regularizer=None,
                 name=None):
        super().__init__(name=name)
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t, d_h, d_w)
        self.pad = (pad_t, pad_h, pad_w)
        self.with_bias = with_bias
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        kt, kh, kw = self.kernel
        fan_in = self.n_input_plane * kt * kh * kw
        fan_out = self.n_output_plane * kt * kh * kw
        w = init_tensor(self, k1, (self.n_output_plane, self.n_input_plane,
                                   kt, kh, kw), fan_in, fan_out, Xavier())
        p = {"weight": w}
        if self.with_bias:
            p["bias"] = init_tensor(self, k2, (self.n_output_plane,),
                                    fan_in, fan_out, Zeros(), kind="bias")
        return {self.name: p}

    def apply(self, params, x, ctx):
        p = self.own(params)
        pads = []
        for i, (pd, k, s) in enumerate(zip(self.pad, self.kernel, self.stride)):
            pads.append(_same_pad(x.shape[2 + i], s, k) if pd == -1 else (pd, pd))
        y = lax.conv_general_dilated(
            x, p["weight"].astype(x.dtype), window_strides=self.stride,
            padding=pads, dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
        if self.with_bias:
            y = y + p["bias"].astype(x.dtype)[None, :, None, None, None]
        return y


class VolumetricFullConvolution(Module):
    """3D transposed convolution (nn/VolumetricFullConvolution.scala)."""

    def __init__(self, n_input_plane, n_output_plane, k_t, k_w, k_h,
                 d_t=1, d_w=1, d_h=1, pad_t=0, pad_w=0, pad_h=0,
                 adj_t=0, adj_w=0, adj_h=0, n_group=1, no_bias=False,
                 w_regularizer=None, b_regularizer=None, name=None):
        super().__init__(name=name)
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t, d_h, d_w)
        self.pad = (pad_t, pad_h, pad_w)
        self.adj = (adj_t, adj_h, adj_w)
        self.n_group = n_group
        self.with_bias = not no_bias
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        kt, kh, kw = self.kernel
        fan_in = self.n_input_plane // self.n_group * kt * kh * kw
        w = init_tensor(self, k1,
                        (self.n_input_plane, self.n_output_plane // self.n_group,
                         kt, kh, kw), fan_in, fan_in, Xavier())
        p = {"weight": w}
        if self.with_bias:
            p["bias"] = init_tensor(self, k2, (self.n_output_plane,),
                                    fan_in, fan_in, Zeros(), kind="bias")
        return {self.name: p}

    def apply(self, params, x, ctx):
        p = self.own(params)
        w = p["weight"].astype(x.dtype)[:, :, ::-1, ::-1, ::-1]
        g = self.n_group
        if g > 1:
            i_g = self.n_input_plane // g
            o_g = self.n_output_plane // g
            kt, kh, kw = self.kernel
            w = (w.reshape(g, i_g, o_g, kt, kh, kw)
                  .transpose(1, 0, 2, 3, 4, 5)
                  .reshape(i_g, g * o_g, kt, kh, kw))
        pads = [(k - 1 - pd, k - 1 - pd + a)
                for k, pd, a in zip(self.kernel, self.pad, self.adj)]
        y = lax.conv_general_dilated(
            x, w, window_strides=(1, 1, 1), padding=pads,
            lhs_dilation=self.stride, feature_group_count=g,
            dimension_numbers=("NCDHW", "IODHW", "NCDHW"))
        if self.with_bias:
            y = y + p["bias"].astype(x.dtype)[None, :, None, None, None]
        return y


class LocallyConnected2D(Module):
    """Conv with untied (per-location) weights (nn/LocallyConnected2D.scala).

    Implemented as patch extraction + batched einsum (one big MXU contraction
    per call) instead of per-location loops.
    """

    def __init__(self, n_input_plane, input_width, input_height, n_output_plane,
                 kernel_w, kernel_h, stride_w=1, stride_h=1, pad_w=0, pad_h=0,
                 propagate_back=True, w_regularizer=None, b_regularizer=None,
                 with_bias=True, format="NCHW", name=None):
        super().__init__(name=name)
        self.n_input_plane = n_input_plane
        self.input_size = (input_height, input_width)
        self.n_output_plane = n_output_plane
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.with_bias = with_bias
        self.format = format
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        kh, kw = self.kernel
        self.out_h = (self.input_size[0] + 2 * pad_h - kh) // stride_h + 1
        self.out_w = (self.input_size[1] + 2 * pad_w - kw) // stride_w + 1

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        kh, kw = self.kernel
        fan_in = self.n_input_plane * kh * kw
        w = init_tensor(self, k1,
                        (self.out_h * self.out_w, self.n_output_plane,
                         self.n_input_plane * kh * kw),
                        fan_in, self.n_output_plane, Xavier())
        p = {"weight": w}
        if self.with_bias:
            p["bias"] = init_tensor(
                self, k2, (self.out_h * self.out_w, self.n_output_plane),
                fan_in, self.n_output_plane, Zeros(), kind="bias")
        return {self.name: p}

    def apply(self, params, x, ctx):
        p = self.own(params)
        if self.format == "NHWC":
            x = jnp.transpose(x, (0, 3, 1, 2))
        kh, kw = self.kernel
        ph, pw = self.pad
        x = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        patches = lax.conv_general_dilated_patches(
            x, (kh, kw), self.stride, [(0, 0), (0, 0)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # patches: (B, C*kh*kw, out_h, out_w)
        b = patches.shape[0]
        patches = patches.reshape(b, -1, self.out_h * self.out_w)
        w = p["weight"].astype(x.dtype)  # (L, O, C*kh*kw)
        y = jnp.einsum("bcl,loc->blo", patches, w)
        if self.with_bias:
            y = y + p["bias"].astype(x.dtype)[None]
        y = y.reshape(b, self.out_h, self.out_w, self.n_output_plane)
        if self.format == "NHWC":
            return y
        return jnp.transpose(y, (0, 3, 1, 2))


class LocallyConnected1D(Module):
    """nn/LocallyConnected1D.scala — untied TemporalConvolution."""

    def __init__(self, n_input_frame, input_frame_size, output_frame_size,
                 kernel_w, stride_w=1, propagate_back=True,
                 w_regularizer=None, b_regularizer=None, name=None):
        super().__init__(name=name)
        self.n_input_frame = n_input_frame
        self.input_frame_size = input_frame_size
        self.output_frame_size = output_frame_size
        self.kernel_w = kernel_w
        self.stride_w = stride_w
        self.n_output_frame = (n_input_frame - kernel_w) // stride_w + 1
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        fan_in = self.input_frame_size * self.kernel_w
        w = init_tensor(self, k1,
                        (self.n_output_frame, self.output_frame_size,
                         fan_in), fan_in, self.output_frame_size, Xavier())
        b = init_tensor(self, k2,
                        (self.n_output_frame, self.output_frame_size),
                        fan_in, self.output_frame_size, Zeros(), kind="bias")
        return {self.name: {"weight": w, "bias": b}}

    def apply(self, params, x, ctx):
        p = self.own(params)
        # x: (B, T, C). Extract windows: (B, L, kernel_w*C)
        idx = (jnp.arange(self.n_output_frame)[:, None] * self.stride_w
               + jnp.arange(self.kernel_w)[None, :])
        windows = x[:, idx, :]  # (B, L, kw, C)
        b = windows.shape[0]
        windows = windows.reshape(b, self.n_output_frame, -1)
        w = p["weight"].astype(x.dtype)
        y = jnp.einsum("blc,loc->blo", windows, w)
        return y + p["bias"].astype(x.dtype)[None]


class SpatialConvolutionMap(Module):
    """Conv with an explicit input→output plane connection table
    (nn/SpatialConvolutionMap.scala; Torch's SpatialConvolutionMap).

    conn_table is (K, 2) 1-based [in_plane, out_plane] pairs, each with its
    own (kh, kw) kernel.  On TPU this lowers to ONE dense masked conv: a
    (out, in, kh, kw) weight whose unconnected pairs are structurally zero
    (mask applied in apply, so AD keeps them zero too) — the MXU is fast
    enough that dense-with-mask beats gather-scatter scheduling.

    full/one-to-one/random tables via the `full_table`/`one_to_one`/
    `random_table` constructors, mirroring the reference companion object.
    """

    def __init__(self, conn_table, kw, kh, dw=1, dh=1, pad_w=0, pad_h=0,
                 with_bias=True, n_input_plane=None, n_output_plane=None,
                 name=None):
        super().__init__(name=name)
        self.conn_table = np.asarray(conn_table, np.int32)
        self.kernel = (kh, kw)
        self.stride = (dh, dw)
        self.pad = (pad_h, pad_w)
        # table max only lower-bounds the plane counts (a random table may
        # skip the last plane) — callers can pass the true sizes
        self.n_input_plane = n_input_plane or int(self.conn_table[:, 0].max())
        self.n_output_plane = (n_output_plane
                               or int(self.conn_table[:, 1].max()))
        self.with_bias = with_bias

    @staticmethod
    def full_table(n_in, n_out):
        return np.array([[i + 1, o + 1] for o in range(n_out)
                         for i in range(n_in)], np.int32)

    @staticmethod
    def one_to_one(n_features):
        return np.array([[i + 1, i + 1] for i in range(n_features)],
                        np.int32)

    @staticmethod
    def random_table(n_in, n_out, n_into, seed=0):
        rs = np.random.RandomState(seed)
        rows = []
        for o in range(n_out):
            for i in rs.choice(n_in, size=n_into, replace=False):
                rows.append([i + 1, o + 1])
        return np.asarray(rows, np.int32)

    def _mask(self):
        m = np.zeros((self.n_output_plane, self.n_input_plane, 1, 1),
                     np.float32)
        m[self.conn_table[:, 1] - 1, self.conn_table[:, 0] - 1] = 1.0
        return m

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        kh, kw = self.kernel
        # Torch init: stdv = 1/sqrt(kW*kH*nInputPlane-ish fan); use per-out
        # fan from the table
        fan_in = max(1, int((self.conn_table[:, 1] ==
                             self.conn_table[0, 1]).sum())) * kh * kw
        w = init_tensor(self, k1, (self.n_output_plane, self.n_input_plane,
                                   kh, kw), fan_in, fan_in, Xavier())
        w = w * jnp.asarray(self._mask())
        p = {"weight": w}
        if self.with_bias:
            p["bias"] = init_tensor(self, k2, (self.n_output_plane,),
                                    fan_in, fan_in, Zeros(), kind="bias")
        return {self.name: p}

    def apply(self, params, x, ctx):
        p = self.own(params)
        if x.shape[1] != self.n_input_plane:
            raise ValueError(
                f"{self.name}: input has {x.shape[1]} planes but the "
                f"connection table implies {self.n_input_plane}; pass "
                "n_input_plane= explicitly")
        w = (p["weight"] * jnp.asarray(self._mask())).astype(x.dtype)
        pads = []
        for i, (pd, k, s) in enumerate(zip(self.pad, self.kernel,
                                           self.stride)):
            pads.append(_same_pad(x.shape[2 + i], s, k) if pd == -1
                        else (pd, pd))
        y = lax.conv_general_dilated(
            x, w, window_strides=self.stride, padding=pads,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        if self.with_bias:
            y = y + p["bias"].astype(x.dtype)[None, :, None, None]
        return y
