"""Shape / indexing manipulation layers.

Reference files: nn/Reshape.scala, View.scala, Squeeze.scala, Unsqueeze.scala,
Transpose.scala, Select.scala, Narrow.scala, Replicate.scala, Padding.scala,
SpatialZeroPadding.scala, Cropping2D.scala, Cropping3D.scala, Contiguous.scala,
InferReshape.scala, Index.scala, Tile.scala, Pack.scala, Reverse.scala,
Masking.scala, Sum.scala, Mean.scala (in keras), Max.scala, Min.scala,
Negative.scala, GradientReversal.scala.

Dimension arguments are 1-based like the reference (Torch convention);
batch dim is dim 0 and is implicitly preserved where the reference does so.
All are pure metadata/gather ops that XLA folds into surrounding kernels.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .module import Module
from ..utils.table import as_list, Table


def _axis(dim, ndim, batch_offset=0):
    """1-based (possibly negative) reference dim -> 0-based axis."""
    if dim < 0:
        return ndim + dim
    return dim - 1 + batch_offset


class Reshape(Module):
    """Reshape non-batch dims to `size` (nn/Reshape.scala). With
    batch_mode=False and matching element count, reshapes the whole tensor."""

    def __init__(self, size, batch_mode=None, name=None):
        super().__init__(name=name)
        self.size = tuple(size)
        self.batch_mode = batch_mode

    def apply(self, params, x, ctx):
        n = int(np.prod(self.size))
        batch = self.batch_mode
        if batch is None:
            # batched iff the per-sample tail (dims after the leading batch
            # dim) matches the target element count — robust for batch size 1,
            # where x.size == n is ambiguous
            batch = ((x.ndim > 1 and int(np.prod(x.shape[1:])) == n)
                     or (x.size != n and x.size % n == 0))
        if batch:
            return x.reshape((x.shape[0],) + self.size)
        return x.reshape(self.size)


class View(Module):
    """nn/View.scala — reshape keeping batch dim; -1 wildcard supported."""

    def __init__(self, *sizes, name=None):
        super().__init__(name=name)
        if len(sizes) == 1 and isinstance(sizes[0], (tuple, list)):
            sizes = tuple(sizes[0])
        self.sizes = tuple(sizes)
        self.num_input_dims = 0

    _serde_extra_attrs = ("num_input_dims",)

    def set_num_input_dims(self, n):
        self.num_input_dims = n
        return self

    def apply(self, params, x, ctx):
        total = int(np.prod([s for s in self.sizes if s != -1]))
        if x.size % total == 0 and x.size != total and -1 not in self.sizes:
            return x.reshape((x.shape[0],) + self.sizes)
        return x.reshape(self.sizes if -1 in self.sizes
                         else (x.shape[0],) + self.sizes)


class InferReshape(Module):
    """Reshape with -1 (infer) and 0 (copy input dim) entries
    (nn/InferReshape.scala)."""

    def __init__(self, size, batch_mode=False, name=None):
        super().__init__(name=name)
        self.size = tuple(size)
        self.batch_mode = batch_mode

    def apply(self, params, x, ctx):
        in_shape = x.shape[1:] if self.batch_mode else x.shape
        out = []
        for i, s in enumerate(self.size):
            if s == 0:
                out.append(in_shape[i])
            else:
                out.append(s)
        if self.batch_mode:
            return x.reshape((x.shape[0],) + tuple(out))
        return x.reshape(tuple(out))


class Squeeze(Module):
    """nn/Squeeze.scala; dim is 1-based, None squeezes all singleton dims."""

    def __init__(self, dim=None, num_input_dims=0, batch_mode=False, name=None):
        super().__init__(name=name)
        self.dim = dim
        self.batch_mode = batch_mode

    def apply(self, params, x, ctx):
        if self.dim is None:
            return jnp.squeeze(x)
        dims = self.dim if isinstance(self.dim, (tuple, list)) else (self.dim,)
        axes = tuple(_axis(d, x.ndim, 1 if self.batch_mode else 0)
                     for d in dims)
        return jnp.squeeze(x, axis=axes)


class Unsqueeze(Module):
    """nn/Unsqueeze.scala; pos is 1-based."""

    def __init__(self, pos, num_input_dims=0, name=None):
        super().__init__(name=name)
        self.pos = pos

    def apply(self, params, x, ctx):
        return jnp.expand_dims(x, axis=self.pos - 1 + 1)  # batch offset


class Transpose(Module):
    """Swap listed (1-based) dim pairs in order (nn/Transpose.scala).
    Per the reference's batch use, pairs address non-batch dims."""

    def __init__(self, permutations, name=None):
        super().__init__(name=name)
        self.permutations = [tuple(p) for p in permutations]

    def apply(self, params, x, ctx):
        perm = list(range(x.ndim))
        for d1, d2 in self.permutations:
            a1, a2 = _axis(d1, x.ndim, 1), _axis(d2, x.ndim, 1)
            perm[a1], perm[a2] = perm[a2], perm[a1]
        return jnp.transpose(x, perm)


class Select(Module):
    """Select index `index` along dim (both 1-based; negative supported)
    (nn/Select.scala)."""

    def __init__(self, dim, index, name=None):
        super().__init__(name=name)
        self.dim = dim
        self.index = index

    def apply(self, params, x, ctx):
        ax = _axis(self.dim, x.ndim)
        idx = self.index - 1 if self.index > 0 else x.shape[ax] + self.index
        return jnp.take(x, idx, axis=ax)


class Narrow(Module):
    """Slice `length` elements from 1-based `offset` along dim (nn/Narrow.scala);
    negative length counts from the end."""

    def __init__(self, dimension, offset, length=1, name=None):
        super().__init__(name=name)
        self.dimension = dimension
        self.offset = offset
        self.length = length

    def apply(self, params, x, ctx):
        ax = _axis(self.dimension, x.ndim)
        size = x.shape[ax]
        start = self.offset - 1 if self.offset > 0 else size + self.offset
        length = self.length if self.length > 0 else size - start + self.length + 1
        return jax.lax.slice_in_dim(x, start, start + length, axis=ax)


class Replicate(Module):
    """Insert a new dim of size nFeatures at `dim` by replication
    (nn/Replicate.scala)."""

    def __init__(self, n_features, dim=1, n_dim=float("inf"), name=None):
        super().__init__(name=name)
        self.n_features = n_features
        self.dim = dim

    def apply(self, params, x, ctx):
        y = jnp.expand_dims(x, axis=self.dim)
        return jnp.repeat(y, self.n_features, axis=self.dim)


class Padding(Module):
    """Pad `pad` entries (negative = before, positive = after) along dim with
    `value` (nn/Padding.scala); dim is 1-based over non-batch dims when
    n_input_dim < input rank."""

    def __init__(self, dim, pad, n_input_dim, value=0.0, n_index=1, name=None):
        super().__init__(name=name)
        self.dim = dim
        self.pad = pad
        self.n_input_dim = n_input_dim
        self.value = value

    def apply(self, params, x, ctx):
        offset = 1 if x.ndim > self.n_input_dim else 0
        ax = self.dim - 1 + offset
        pads = [(0, 0)] * x.ndim
        pads[ax] = (-self.pad, 0) if self.pad < 0 else (0, self.pad)
        return jnp.pad(x, pads, constant_values=self.value)


class SpatialZeroPadding(Module):
    """Zero-pad H/W of NCHW input (nn/SpatialZeroPadding.scala); negative
    padding crops.  format='NHWC' pads channels-last input."""

    def __init__(self, pad_left, pad_right=None, pad_top=None, pad_bottom=None,
                 format="NCHW", name=None):
        super().__init__(name=name)
        if pad_right is None:
            pad_right = pad_top = pad_bottom = pad_left
        self.pads = (pad_left, pad_right, pad_top, pad_bottom)
        self.format = format

    def apply(self, params, x, ctx):
        l, r, t, b = self.pads
        hax = 2 if self.format == "NCHW" else 1
        if min(self.pads) < 0:
            h, w = x.shape[hax], x.shape[hax + 1]
            sl = [slice(None)] * x.ndim
            sl[hax] = slice(max(0, -t), h - max(0, -b))
            sl[hax + 1] = slice(max(0, -l), w - max(0, -r))
            x = x[tuple(sl)]
            l, r, t, b = [max(0, v) for v in (l, r, t, b)]
        pads = [(0, 0)] * x.ndim
        pads[hax] = (t, b)
        pads[hax + 1] = (l, r)
        return jnp.pad(x, pads)


class Cropping2D(Module):
    """Crop H/W (nn/Cropping2D.scala)."""

    def __init__(self, height_crop, width_crop, format="NCHW", name=None):
        super().__init__(name=name)
        self.height_crop = tuple(height_crop)
        self.width_crop = tuple(width_crop)
        self.format = format

    def apply(self, params, x, ctx):
        (t, b), (l, r) = self.height_crop, self.width_crop
        h_ax, w_ax = (2, 3) if self.format == "NCHW" else (1, 2)
        sl = [slice(None)] * x.ndim
        sl[h_ax] = slice(t, x.shape[h_ax] - b)
        sl[w_ax] = slice(l, x.shape[w_ax] - r)
        return x[tuple(sl)]


class Cropping3D(Module):
    """nn/Cropping3D.scala for NCDHW ('channel_first') or NDHWC."""

    def __init__(self, dim1_crop, dim2_crop, dim3_crop, format="channel_first",
                 name=None):
        super().__init__(name=name)
        self.crops = (tuple(dim1_crop), tuple(dim2_crop), tuple(dim3_crop))
        self.format = format

    def apply(self, params, x, ctx):
        axes = (2, 3, 4) if self.format == "channel_first" else (1, 2, 3)
        sl = [slice(None)] * x.ndim
        for ax, (lo, hi) in zip(axes, self.crops):
            sl[ax] = slice(lo, x.shape[ax] - hi)
        return x[tuple(sl)]


class Contiguous(Module):
    """nn/Contiguous.scala — identity on TPU (XLA manages layout)."""

    def apply(self, params, x, ctx):
        return x


class Index(Module):
    """Table input {tensor, 1-based indices}; gathers along dim (nn/Index.scala)."""

    def __init__(self, dimension, name=None):
        super().__init__(name=name)
        self.dimension = dimension

    def apply(self, params, x, ctx):
        t, idx = as_list(x)
        return jnp.take(t, idx.astype(jnp.int32) - 1,
                        axis=_axis(self.dimension, t.ndim))


class Tile(Module):
    """Repeat `copies` times along dim (nn/Tile.scala)."""

    def __init__(self, dim=1, copies=2, name=None):
        super().__init__(name=name)
        self.dim = dim
        self.copies = copies

    def apply(self, params, x, ctx):
        reps = [1] * x.ndim
        reps[_axis(self.dim, x.ndim)] = self.copies
        return jnp.tile(x, reps)


class Pack(Module):
    """Stack a table of tensors along a new (1-based) dim (nn/Pack.scala)."""

    def __init__(self, dimension, name=None):
        super().__init__(name=name)
        self.dimension = dimension

    def apply(self, params, x, ctx):
        return jnp.stack(as_list(x), axis=self.dimension - 1)


class Reverse(Module):
    """Reverse along dim (nn/Reverse.scala)."""

    def __init__(self, dimension=1, is_inplace=False, name=None):
        super().__init__(name=name)
        self.dimension = dimension

    def apply(self, params, x, ctx):
        return jnp.flip(x, axis=self.dimension - 1)


class Masking(Module):
    """Zero out timesteps equal to mask_value (keras-style Masking, present in
    reference keras layer set)."""

    def __init__(self, mask_value=0.0, name=None):
        super().__init__(name=name)
        self.mask_value = mask_value

    def apply(self, params, x, ctx):
        keep = jnp.any(x != self.mask_value, axis=-1, keepdims=True)
        return x * keep.astype(x.dtype)


class Sum(Module):
    """Sum along dim, optional mean/squeeze (nn/Sum.scala)."""

    def __init__(self, dimension=1, n_input_dims=-1, size_average=False,
                 squeeze=True, name=None):
        super().__init__(name=name)
        self.dimension = dimension
        self.size_average = size_average
        self.squeeze = squeeze

    def apply(self, params, x, ctx):
        ax = _axis(self.dimension, x.ndim)
        y = jnp.mean(x, axis=ax, keepdims=not self.squeeze) if self.size_average \
            else jnp.sum(x, axis=ax, keepdims=not self.squeeze)
        return y


class Max(Module):
    """Max along dim (nn/Max.scala); returns values only (reference returns
    values; indices variant is in ops)."""

    def __init__(self, dim=1, num_input_dims=0, name=None):
        super().__init__(name=name)
        self.dim = dim

    def apply(self, params, x, ctx):
        return jnp.max(x, axis=_axis(self.dim, x.ndim))


class Min(Module):
    """nn/Min.scala"""

    def __init__(self, dim=1, num_input_dims=0, name=None):
        super().__init__(name=name)
        self.dim = dim

    def apply(self, params, x, ctx):
        return jnp.min(x, axis=_axis(self.dim, x.ndim))


class Mean(Module):
    """Mean along 1-based dim (nn/Mean.scala)."""

    def __init__(self, dimension=1, n_input_dims=-1, squeeze=True, name=None):
        super().__init__(name=name)
        self.dimension = dimension
        self.squeeze = squeeze

    def apply(self, params, x, ctx):
        return jnp.mean(x, axis=_axis(self.dimension, x.ndim),
                        keepdims=not self.squeeze)


class Negative(Module):
    """nn/Negative.scala"""

    def apply(self, params, x, ctx):
        return -x


class GradientReversal(Module):
    """Identity forward, -lambda * grad backward (nn/GradientReversal.scala)."""

    def __init__(self, the_lambda=1.0, name=None):
        super().__init__(name=name)
        self.the_lambda = the_lambda

    def apply(self, params, x, ctx):
        lam = self.the_lambda

        @jax.custom_vjp
        def rev(v):
            return v

        def fwd(v):
            return v, None

        def bwd(_, g):
            return (jax.tree_util.tree_map(lambda t: -lam * t, g),)

        rev.defvjp(fwd, bwd)
        return rev(x)


class SplitAndSelect(Module):
    """Split along dim into n parts, return the index-th (nn/tf/SplitAndSelect.scala)."""

    def __init__(self, dimension, index, num_split, name=None):
        super().__init__(name=name)
        self.dimension = dimension
        self.index = index
        self.num_split = num_split

    def apply(self, params, x, ctx):
        parts = jnp.split(x, self.num_split, axis=_axis(self.dimension, x.ndim))
        return parts[self.index - 1]


class StrideSlice(Module):
    """Strided slice, specs = list of (dim, start, stop, step) 1-based
    (nn/tf/StrideSlice.scala)."""

    def __init__(self, specs, name=None):
        super().__init__(name=name)
        self.specs = specs

    def apply(self, params, x, ctx):
        sl = [slice(None)] * x.ndim
        for dim, start, stop, step in self.specs:
            sl[dim - 1] = slice(start - 1, stop - 1, step)
        return x[tuple(sl)]
