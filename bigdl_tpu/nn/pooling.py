"""Pooling / resampling layers.

Reference files: nn/SpatialMaxPooling.scala, SpatialAveragePooling.scala,
VolumetricMaxPooling.scala, VolumetricAveragePooling.scala,
TemporalMaxPooling.scala, UpSampling1D/2D/3D.scala, ResizeBilinear.scala.

All pooling lowers to ``lax.reduce_window`` (vectorized on VPU); no
hand-written index bookkeeping as in the reference NNPrimitive code.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from .module import Module


def _pool_pads(in_size, k, s, pad, ceil_mode):
    """Reference pooling geometry: out = floor_or_ceil((in + 2p - k)/s) + 1.

    Returns (lo, hi) padding so reduce_window matches, padding with the
    reduction identity (handled by caller via init value).
    """
    if pad == -1:  # SAME, reference keras-style
        out = -(-in_size // s)
        total = max(0, (out - 1) * s + k - in_size)
        return total // 2, total - total // 2
    if ceil_mode:
        out = int(np.ceil((in_size + 2 * pad - k) / s)) + 1
        # torch rule: last window must start inside the (padded) input
        if (out - 1) * s >= in_size + pad:
            out -= 1
    else:
        out = int(np.floor((in_size + 2 * pad - k) / s)) + 1
    hi = max(0, (out - 1) * s + k - in_size - pad)
    return pad, hi


class SpatialMaxPooling(Module):
    """nn/SpatialMaxPooling.scala; pad=-1 means SAME."""

    def __init__(self, kw, kh, dw=None, dh=None, pad_w=0, pad_h=0,
                 format="NCHW", ceil_mode=False, name=None):
        super().__init__(name=name)
        self.kernel = (kh, kw)
        self.stride = (dh or kh, dw or kw)
        self.pad = (pad_h, pad_w)
        self.format = format
        self.ceil_mode = ceil_mode

    _serde_extra_attrs = ("ceil_mode",)

    def ceil(self):
        self.ceil_mode = True
        return self

    def floor(self):
        self.ceil_mode = False
        return self

    def apply(self, params, x, ctx):
        nchw = self.format == "NCHW"
        hs = x.shape[2:4] if nchw else x.shape[1:3]
        pads = [_pool_pads(hs[i], self.kernel[i], self.stride[i], self.pad[i],
                           self.ceil_mode) for i in range(2)]
        if nchw:
            window = (1, 1) + self.kernel
            strides = (1, 1) + self.stride
            padding = [(0, 0), (0, 0)] + pads
        else:
            window = (1,) + self.kernel + (1,)
            strides = (1,) + self.stride + (1,)
            padding = [(0, 0)] + pads + [(0, 0)]
        return lax.reduce_window(x, -jnp.inf, lax.max, window, strides, padding)


class SpatialAveragePooling(Module):
    """nn/SpatialAveragePooling.scala. count_include_pad matches torch
    semantics; global_pooling pools the whole plane."""

    def __init__(self, kw, kh, dw=1, dh=1, pad_w=0, pad_h=0,
                 global_pooling=False, ceil_mode=False,
                 count_include_pad=True, divide=True, format="NCHW",
                 name=None):
        super().__init__(name=name)
        self.kernel = (kh, kw)
        self.stride = (dh, dw)
        self.pad = (pad_h, pad_w)
        self.global_pooling = global_pooling
        self.ceil_mode = ceil_mode
        self.count_include_pad = count_include_pad
        self.divide = divide
        self.format = format

    _serde_extra_attrs = ("ceil_mode",)

    def ceil(self):
        self.ceil_mode = True
        return self

    def apply(self, params, x, ctx):
        nchw = self.format == "NCHW"
        hs = x.shape[2:4] if nchw else x.shape[1:3]
        kernel = tuple(hs) if self.global_pooling else self.kernel
        stride = (1, 1) if self.global_pooling else self.stride
        pads = [(0, 0), (0, 0)] if self.global_pooling else \
            [_pool_pads(hs[i], kernel[i], stride[i], self.pad[i],
                        self.ceil_mode) for i in range(2)]
        if nchw:
            window = (1, 1) + tuple(kernel)
            strides = (1, 1) + tuple(stride)
            padding = [(0, 0), (0, 0)] + pads
        else:
            window = (1,) + tuple(kernel) + (1,)
            strides = (1,) + tuple(stride) + (1,)
            padding = [(0, 0)] + pads + [(0, 0)]
        s = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
        if not self.divide:
            return s
        if self.count_include_pad:
            return s / float(np.prod(kernel))
        ones = jnp.ones_like(x)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
        return s / counts


class VolumetricMaxPooling(Module):
    """nn/VolumetricMaxPooling.scala over (B, C, D, H, W)."""

    def __init__(self, k_t, k_w, k_h, d_t=None, d_w=None, d_h=None,
                 pad_t=0, pad_w=0, pad_h=0, name=None):
        super().__init__(name=name)
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t or k_t, d_h or k_h, d_w or k_w)
        self.pad = (pad_t, pad_h, pad_w)

    def apply(self, params, x, ctx):
        pads = [_pool_pads(x.shape[2 + i], self.kernel[i], self.stride[i],
                           self.pad[i], False) for i in range(3)]
        return lax.reduce_window(
            x, -jnp.inf, lax.max, (1, 1) + self.kernel, (1, 1) + self.stride,
            [(0, 0), (0, 0)] + pads)


class VolumetricAveragePooling(Module):
    """nn/VolumetricAveragePooling.scala."""

    def __init__(self, k_t, k_w, k_h, d_t=None, d_w=None, d_h=None,
                 pad_t=0, pad_w=0, pad_h=0, count_include_pad=True,
                 ceil_mode=False, name=None):
        super().__init__(name=name)
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t or k_t, d_h or k_h, d_w or k_w)
        self.pad = (pad_t, pad_h, pad_w)
        self.count_include_pad = count_include_pad
        self.ceil_mode = ceil_mode

    def apply(self, params, x, ctx):
        pads = [_pool_pads(x.shape[2 + i], self.kernel[i], self.stride[i],
                           self.pad[i], self.ceil_mode) for i in range(3)]
        s = lax.reduce_window(
            x, 0.0, lax.add, (1, 1) + self.kernel, (1, 1) + self.stride,
            [(0, 0), (0, 0)] + pads)
        if self.count_include_pad:
            return s / float(np.prod(self.kernel))
        counts = lax.reduce_window(
            jnp.ones_like(x), 0.0, lax.add, (1, 1) + self.kernel,
            (1, 1) + self.stride, [(0, 0), (0, 0)] + pads)
        return s / counts


class TemporalMaxPooling(Module):
    """nn/TemporalMaxPooling.scala over (B, T, C)."""

    def __init__(self, k_w, d_w=None, name=None):
        super().__init__(name=name)
        self.k_w = k_w
        self.d_w = d_w or k_w

    def apply(self, params, x, ctx):
        return lax.reduce_window(
            x, -jnp.inf, lax.max, (1, self.k_w, 1), (1, self.d_w, 1),
            [(0, 0), (0, 0), (0, 0)])


class UpSampling1D(Module):
    """Repeat each timestep `length` times (nn/UpSampling1D.scala); (B,T,C)."""

    def __init__(self, length, name=None):
        super().__init__(name=name)
        self.length = length

    def apply(self, params, x, ctx):
        return jnp.repeat(x, self.length, axis=1)


class UpSampling2D(Module):
    """Nearest-neighbour upsample (nn/UpSampling2D.scala), NCHW."""

    def __init__(self, size, format="NCHW", name=None):
        super().__init__(name=name)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.format = format

    def apply(self, params, x, ctx):
        h_ax, w_ax = (2, 3) if self.format == "NCHW" else (1, 2)
        x = jnp.repeat(x, self.size[0], axis=h_ax)
        return jnp.repeat(x, self.size[1], axis=w_ax)


class UpSampling3D(Module):
    """nn/UpSampling3D.scala, NCDHW."""

    def __init__(self, size, name=None):
        super().__init__(name=name)
        self.size = (size, size, size) if isinstance(size, int) else tuple(size)

    def apply(self, params, x, ctx):
        for i, s in enumerate(self.size):
            x = jnp.repeat(x, s, axis=2 + i)
        return x


class ResizeBilinear(Module):
    """Bilinear resize (nn/ResizeBilinear.scala), NCHW or NHWC input."""

    def __init__(self, output_height, output_width, align_corners=False,
                 data_format="NCHW", name=None):
        super().__init__(name=name)
        self.out_hw = (output_height, output_width)
        self.align_corners = align_corners
        self.format = data_format

    def apply(self, params, x, ctx):
        import jax.image
        nchw = self.format == "NCHW"
        if nchw:
            shape = x.shape[:2] + self.out_hw
        else:
            shape = (x.shape[0],) + self.out_hw + (x.shape[3],)
        # jax.image.resize implements half-pixel-centers (align_corners=False)
        if not self.align_corners:
            return jax.image.resize(x, shape, method="bilinear")
        h_ax, w_ax = (2, 3) if nchw else (1, 2)
        in_h, in_w = x.shape[h_ax], x.shape[w_ax]
        oh, ow = self.out_hw
        ys = jnp.linspace(0, in_h - 1, oh)
        xs = jnp.linspace(0, in_w - 1, ow)
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, in_h - 1)
        y1 = jnp.clip(y0 + 1, 0, in_h - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, in_w - 1)
        x1 = jnp.clip(x0 + 1, 0, in_w - 1)
        wy = (ys - y0).reshape(-1, 1)
        wx = (xs - x0).reshape(1, -1)
        def gather(yi, xi):
            g = jnp.take(x, yi, axis=h_ax)
            return jnp.take(g, xi, axis=w_ax)
        if nchw:
            wy_b, wx_b = wy[None, None], wx[None, None]
        else:
            wy_b, wx_b = wy[None, :, :, None], wx[None, :, :, None]
        top = gather(y0, x0) * (1 - wx_b) + gather(y0, x1) * wx_b
        bot = gather(y1, x0) * (1 - wx_b) + gather(y1, x1) * wx_b
        return top * (1 - wy_b) + bot * wy_b
