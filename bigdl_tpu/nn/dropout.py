"""Stochastic regularization layers.

Reference files: nn/Dropout.scala, GaussianDropout.scala, GaussianNoise.scala,
GaussianSampler.scala, SpatialDropout1D/2D/3D.scala.

RNG keys are derived per-module from the ctx key (fold_in on the module uid),
so a single key passed to the train step drives every stochastic layer
deterministically — reproducible and jit-stable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .module import Module
from ..utils.table import as_list


class Dropout(Module):
    """Inverted dropout, scaling by 1/(1-p) at train time when scale=True
    (nn/Dropout.scala)."""

    def __init__(self, init_p=0.5, inplace=False, scale=True, name=None):
        super().__init__(name=name)
        self.p = init_p
        self.scale = scale

    _serde_extra_attrs = ("p",)

    def set_p(self, p):
        self.p = p
        return self

    def apply(self, params, x, ctx):
        if not ctx.training or self.p <= 0.0:
            return x
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(ctx.rng(self), keep, x.shape)
        y = jnp.where(mask, x, 0.0)
        return y / keep if self.scale else y


class GaussianDropout(Module):
    """Multiplicative N(1, p/(1-p)) noise at train time (nn/GaussianDropout.scala)."""

    def __init__(self, rate, name=None):
        super().__init__(name=name)
        self.rate = rate

    def apply(self, params, x, ctx):
        if not ctx.training:
            return x
        stddev = jnp.sqrt(self.rate / (1.0 - self.rate))
        noise = 1.0 + stddev * jax.random.normal(ctx.rng(self), x.shape, x.dtype)
        return x * noise


class GaussianNoise(Module):
    """Additive N(0, stddev) noise at train time (nn/GaussianNoise.scala)."""

    def __init__(self, stddev, name=None):
        super().__init__(name=name)
        self.stddev = stddev

    def apply(self, params, x, ctx):
        if not ctx.training:
            return x
        return x + self.stddev * jax.random.normal(ctx.rng(self), x.shape, x.dtype)


class GaussianSampler(Module):
    """Sample from N(mean, exp(logvar)) given a table {mean, logvar}
    (nn/GaussianSampler.scala — the VAE reparameterization trick)."""

    def apply(self, params, x, ctx):
        mean, log_var = as_list(x)
        eps = jax.random.normal(ctx.rng(self), mean.shape, mean.dtype)
        return mean + jnp.exp(0.5 * log_var) * eps


class SpatialDropout1D(Module):
    """Drop whole channels of (B, T, C) (nn/SpatialDropout1D.scala)."""

    def __init__(self, init_p=0.5, name=None):
        super().__init__(name=name)
        self.p = init_p

    def apply(self, params, x, ctx):
        if not ctx.training or self.p <= 0.0:
            return x
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(ctx.rng(self), keep,
                                    (x.shape[0], 1, x.shape[2]))
        return jnp.where(mask, x, 0.0)


class SpatialDropout2D(Module):
    """Drop whole feature maps of NCHW/NHWC input (nn/SpatialDropout2D.scala)."""

    def __init__(self, init_p=0.5, format="NCHW", name=None):
        super().__init__(name=name)
        self.p = init_p
        self.format = format

    def apply(self, params, x, ctx):
        if not ctx.training or self.p <= 0.0:
            return x
        keep = 1.0 - self.p
        shape = ((x.shape[0], x.shape[1], 1, 1) if self.format == "NCHW"
                 else (x.shape[0], 1, 1, x.shape[3]))
        mask = jax.random.bernoulli(ctx.rng(self), keep, shape)
        return jnp.where(mask, x, 0.0)


class SpatialDropout3D(Module):
    """nn/SpatialDropout3D.scala for NCDHW/NDHWC input."""

    def __init__(self, init_p=0.5, format="NCDHW", name=None):
        super().__init__(name=name)
        self.p = init_p
        self.format = format

    def apply(self, params, x, ctx):
        if not ctx.training or self.p <= 0.0:
            return x
        keep = 1.0 - self.p
        shape = ((x.shape[0], x.shape[1], 1, 1, 1) if self.format == "NCDHW"
                 else (x.shape[0], 1, 1, 1, x.shape[4]))
        mask = jax.random.bernoulli(ctx.rng(self), keep, shape)
        return jnp.where(mask, x, 0.0)
