"""Containers (≙ nn/Container.scala, Sequential.scala, Concat.scala,
ConcatTable.scala, ParallelTable.scala, MapTable.scala, Bottle.scala).

Containers compose children's pure ``apply`` functions; XLA sees one fused
graph, so there is no per-layer dispatch overhead at run time.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils.table import Table, as_list
from .module import Module


class Container(Module):
    """Base of all multi-child modules (nn/Container.scala): owns a
    children list, aggregates their params/state, forwards by composition."""
    def __init__(self, *mods, name=None):
        super().__init__(name=name)
        self._children = list(mods)

    def add(self, module):
        self._children.append(module)
        return self

    def children(self):
        return list(self._children)

    def _serde_restore_children(self, children):
        self._children = [c for c in children if c is not None]

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return self._children[i]

    def init(self, rng):
        params = {}
        for i, m in enumerate(self._children):
            params.update(m.init(jax.random.fold_in(rng, i)))
        return params

    def initial_state(self):
        state = {}
        for m in self._children:
            state.update(m.initial_state())
        return state

    def __repr__(self):
        inner = ", ".join(repr(c) for c in self._children)
        return f"{type(self).__name__}({inner})"


class Sequential(Container):
    """Feed each child the previous child's output (nn/Sequential.scala)."""

    def apply(self, params, x, ctx):
        for m in self._children:
            x = m.apply(params, x, ctx)
        return x


class Concat(Container):
    """Apply each child to the same input, concat outputs along `dimension`
    (1-based, matching nn/Concat.scala)."""

    def __init__(self, dimension, *mods, name=None):
        super().__init__(*mods, name=name)
        self.dimension = dimension

    def apply(self, params, x, ctx):
        outs = [m.apply(params, x, ctx) for m in self._children]
        return jnp.concatenate(outs, axis=self.dimension - 1)


class ConcatTable(Container):
    """Apply each child to the same input, return a Table of outputs
    (nn/ConcatTable.scala)."""

    def apply(self, params, x, ctx):
        return Table(*[m.apply(params, x, ctx) for m in self._children])


class ParallelTable(Container):
    """i-th child gets i-th element of the input table (nn/ParallelTable.scala)."""

    def apply(self, params, x, ctx):
        xs = as_list(x)
        if len(xs) != len(self._children):
            raise ValueError(
                f"{self.name}: input table size {len(xs)} != children {len(self._children)}")
        return Table(*[m.apply(params, e, ctx)
                       for m, e in zip(self._children, xs)])


class MapTable(Container):
    """Apply one shared module to every element of the input table
    (nn/MapTable.scala). Parameters are shared (single child)."""

    def __init__(self, module=None, name=None):
        super().__init__(*( [module] if module is not None else [] ), name=name)

    def apply(self, params, x, ctx):
        m = self._children[0]
        return Table(*[m.apply(params, e, ctx) for e in as_list(x)])


class Bottle(Container):
    """Reshape a high-dim input to 2D, apply the child, reshape back
    (nn/Bottle.scala). `n_input_dim` counts dims the child consumes."""

    def __init__(self, module, n_input_dim=2, n_output_dim=None, name=None):
        super().__init__(module, name=name)
        self.n_input_dim = n_input_dim
        self.n_output_dim = n_output_dim or n_input_dim

    def apply(self, params, x, ctx):
        shape = x.shape
        lead = shape[:len(shape) - self.n_input_dim + 1]
        flat = x.reshape((-1,) + shape[len(shape) - self.n_input_dim + 1:])
        y = self._children[0].apply(params, flat, ctx)
        return y.reshape(lead + y.shape[1:])


class Identity(Module):
    """nn/Identity.scala"""

    def apply(self, params, x, ctx):
        return x


class Echo(Module):
    """Print activity shape when tracing (nn/Echo.scala — debugging aid)."""

    def apply(self, params, x, ctx):
        for leaf in jax.tree_util.tree_leaves(x):
            print(f"[{self.name}] shape={getattr(leaf, 'shape', None)} "
                  f"dtype={getattr(leaf, 'dtype', None)}")
        return x


class Remat(Container):
    """Rematerialization wrapper: recompute the child's activations in
    the backward pass instead of storing them (``jax.checkpoint``) — the
    HBM-for-FLOPs trade that unlocks larger batch sizes on TPU.  State
    updates and side losses cross the checkpoint boundary functionally,
    so BN statistics behave exactly as without the wrapper.

    Transparent for parameters: ``init``/``initial_state`` delegate to
    the child with the SAME rng (no extra fold), so a wrapped model
    yields identical param/state trees to the unwrapped one.  To keep
    auto-generated module NAMES identical too, wrap AFTER the whole
    model is constructed (see resnet.build(remat=True)) — a Remat
    created mid-build would advance the global uid counter and shift
    every later auto name, breaking checkpoint compatibility.

    No reference counterpart (Spark executors recompute nothing); this
    is the TPU-native memory lever (SURVEY 'HBM bandwidth' design note).
    """

    def __init__(self, child=None, name=None):
        super().__init__(*([child] if child is not None else []), name=name)

    def init(self, rng):
        return self._children[0].init(rng)

    def initial_state(self):
        return self._children[0].initial_state()

    def apply(self, params, x, ctx):
        from .module import Ctx
        child = self._children[0]

        def f(p, xx):
            sub = Ctx(state=ctx.state, training=ctx.training,
                      rng_key=ctx.rng_key)
            y = child.apply(p, xx, sub)
            return y, (dict(sub.new_state), tuple(sub.side_losses))

        y, (upd, side) = jax.checkpoint(f)(params, x)
        ctx.new_state.update(upd)
        ctx.side_losses.extend(side)
        return y
