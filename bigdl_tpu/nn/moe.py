"""Mixture-of-Experts FFN with expert parallelism (TPU-era addition; the
reference has no MoE — this extends the transformer flagship the way
GShard/Switch-Transformer do, mapped to the 'ep' mesh axis).

TPU-first design: routing is ONE softmax + top-k, dispatch/combine are
dense one-hot einsums over a fixed capacity per expert (static shapes; no
sorting, no ragged tensors), and the expert FFN is a single batched
einsum over the leading expert dim.  Under GSPMD the expert dim is
sharded over the 'ep' mesh axis (and d_ff over 'tp'), so the partitioner
lowers dispatch/combine to all-to-alls over ICI and each chip runs only
its local experts.

Load-balancing auxiliary loss (Switch Transformer eq. 4) rides on
``ctx.add_loss`` so every training driver that sums side losses
(make_train_step, DistriOptimizer, SpmdTrainer) picks it up.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .module import Module


class SwitchFFN(Module):
    """Top-k routed SwiGLU experts with fixed capacity.

    Input (B, S, d_model) -> output (B, S, d_model).  ``n_experts`` is
    sharded over the 'ep' mesh axis when present (pspec below);
    ``capacity_factor`` bounds tokens per expert at
    ceil(top_k * tokens / n_experts * capacity_factor) — overflow tokens
    are dropped (their combine weight is zero), underflow slots compute
    zeros, exactly as in Switch Transformer.
    """

    def __init__(self, d_model, d_ff, n_experts, top_k=1,
                 capacity_factor=1.25, aux_loss_weight=1e-2,
                 router_noise=0.0, name=None):
        super().__init__(name=name)
        if top_k not in (1, 2):
            raise ValueError("top_k must be 1 or 2")
        self.d_model = d_model
        self.d_ff = d_ff
        self.n_experts = n_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.aux_loss_weight = aux_loss_weight
        self.router_noise = router_noise
        self.pspec = {"router": P(None, None),
                      "w1": P("ep", None, "tp"),
                      "w3": P("ep", None, "tp"),
                      "w2": P("ep", "tp", None)}

    def init(self, rng):
        k0, k1, k2, k3 = jax.random.split(rng, 4)
        E, D, F = self.n_experts, self.d_model, self.d_ff
        s_in, s_out = D ** -0.5, F ** -0.5
        return {self.name: {
            "router": jax.random.normal(k0, (D, E), jnp.float32) * s_in,
            "w1": jax.random.normal(k1, (E, D, F), jnp.float32) * s_in,
            "w3": jax.random.normal(k3, (E, D, F), jnp.float32) * s_in,
            "w2": jax.random.normal(k2, (E, F, D), jnp.float32) * s_out,
        }}

    def _capacity(self, n_tokens):
        cap = int(self.top_k * n_tokens / self.n_experts
                  * self.capacity_factor + 0.999)
        return max(cap, 1)

    def apply(self, params, x, ctx):
        p = self.own(params)
        dt = x.dtype
        B, S, D = x.shape
        E = self.n_experts
        N = B * S
        C = self._capacity(N)
        xt = x.reshape(N, D)

        # ---- routing (fp32 for a stable softmax) --------------------- #
        logits = jnp.dot(xt.astype(jnp.float32), p["router"])
        if ctx.training and self.router_noise > 0.0:
            logits = logits + self.router_noise * jax.random.normal(
                ctx.rng(self), logits.shape)
        probs = jax.nn.softmax(logits, axis=-1)            # (N, E)

        gates = jnp.zeros((N, E), jnp.float32)
        masked = probs
        for _ in range(self.top_k):
            idx = jnp.argmax(masked, axis=-1)
            onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)
            gates = gates + onehot * probs
            masked = masked * (1.0 - onehot)
        sel = gates > 0.0                                   # (N, E) bool

        # ---- capacity assignment: position of each token in its expert #
        pos = jnp.cumsum(sel.astype(jnp.int32), axis=0) - 1  # (N, E)
        keep = sel & (pos < C)
        # dispatch/combine tensors (N, E, C): one-hot over capacity slots
        slot = jax.nn.one_hot(jnp.where(keep, pos, -1), C,
                              dtype=jnp.float32)            # (N, E, C)
        combine = slot * gates[..., None]                   # weights in slots

        # ---- expert computation (batched over E) --------------------- #
        expert_in = jnp.einsum("nec,nd->ecd", slot.astype(dt), xt)
        h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in,
                                   p["w1"].astype(dt))) \
            * jnp.einsum("ecd,edf->ecf", expert_in, p["w3"].astype(dt))
        expert_out = jnp.einsum("ecf,efd->ecd", h, p["w2"].astype(dt))
        out = jnp.einsum("nec,ecd->nd", combine.astype(dt), expert_out)

        # ---- load-balancing aux loss (Switch eq. 4) ------------------ #
        if ctx.training and self.aux_loss_weight > 0.0:
            frac_tokens = jnp.mean(sel.astype(jnp.float32), axis=0)
            frac_probs = jnp.mean(probs, axis=0)
            aux = E * jnp.sum(frac_tokens * frac_probs) / self.top_k
            ctx.add_loss(self.aux_loss_weight * aux.astype(jnp.float32))

        return out.reshape(B, S, D)
