"""Normalization layers.

Reference files: nn/BatchNormalization.scala, SpatialBatchNormalization.scala,
SpatialCrossMapLRN.scala, SpatialWithinChannelLRN.scala,
SpatialDivisiveNormalization.scala, SpatialSubtractiveNormalization.scala,
SpatialContrastiveNormalization.scala, Normalize.scala, NormalizeScale.scala.

Batch-norm running stats live in the ctx state dicts (the functional state
pytree), not in mutable fields — the whole train step stays pure/jittable.
Under data parallelism the batch statistics are computed per shard exactly
like the reference's per-partition BN; cross-replica sync-BN is available via
``sync_axis`` (psum over the mesh axis).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .module import Module


from functools import partial as _partial


@_partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_train(x, gamma, beta, channel_axis, eps):
    """Training-mode BN core with a hand-fused backward.

    Autodiff of the mean/var formulation sweeps the activations ~5 times in
    the backward; the classic closed-form BN gradient needs 2 (one fused
    reduction pass for dbeta/dgamma, one elementwise pass for dx).  BN is
    HBM-bound, so passes are the whole cost on TPU.
    Returns (y, mean, var); mean/var feed running stats only (their
    cotangents are treated as zero — running stats are aux state, never
    differentiated)."""
    y, mean, var, _ = _bn_train_fwd_impl(x, gamma, beta, channel_axis, eps)
    return y, mean, var


def _bn_train_fwd_impl(x, gamma, beta, channel_axis, eps):
    axes = tuple(i for i in range(x.ndim) if i != channel_axis)
    mean = jnp.mean(x, axis=axes, dtype=jnp.float32)
    m2 = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axes)
    var = jnp.maximum(m2 - jnp.square(mean), 0.0)
    inv = lax.rsqrt(var + eps)
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]
    scale = (gamma * inv).reshape(shape).astype(x.dtype)
    shift = (beta - gamma * mean * inv).reshape(shape).astype(x.dtype)
    y = x * scale + shift
    return y, mean, var, (x, gamma, mean, inv)


def _bn_train_fwd(x, gamma, beta, channel_axis, eps):
    y, mean, var, res = _bn_train_fwd_impl(x, gamma, beta, channel_axis, eps)
    return (y, mean, var), res


def _bn_train_bwd(channel_axis, eps, res, cts):
    dy, _dmean, _dvar = cts  # mean/var cotangents: aux-only, zero
    x, gamma, mean, inv = res
    axes = tuple(i for i in range(x.ndim) if i != channel_axis)
    n = x.size // x.shape[channel_axis]
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]
    mean_b = mean.reshape(shape)
    inv_b = inv.reshape(shape)
    dy32 = dy.astype(jnp.float32)
    xhat = (x.astype(jnp.float32) - mean_b) * inv_b
    dbeta = jnp.sum(dy32, axis=axes)
    dgamma = jnp.sum(dy32 * xhat, axis=axes)
    coef = (gamma * inv).reshape(shape)
    dx = coef * (dy32 - (dbeta.reshape(shape)
                         + xhat * dgamma.reshape(shape)) / n)
    return dx.astype(x.dtype), dgamma, dbeta


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


class BatchNormalization(Module):
    """BN over (B, C) or (B, C, ...) with stats on all non-channel dims
    (nn/BatchNormalization.scala — channel dim is 2nd, i.e. axis 1)."""

    channel_axis = 1

    def __init__(self, n_output, eps=1e-5, momentum=0.1, affine=True,
                 sync_axis=None, name=None):
        super().__init__(name=name)
        self.n_output = n_output
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        self.sync_axis = sync_axis

    def init(self, rng):
        if not self.affine:
            return {}
        from .init import init_tensor, Ones, Zeros
        k1, k2 = jax.random.split(rng)
        return {self.name: {
            "weight": init_tensor(self, k1, (self.n_output,), self.n_output,
                                  self.n_output, Ones()),
            "bias": init_tensor(self, k2, (self.n_output,), self.n_output,
                                self.n_output, Zeros(), kind="bias"),
        }}

    def initial_state(self):
        return {self.name: {
            "running_mean": jnp.zeros((self.n_output,), jnp.float32),
            "running_var": jnp.ones((self.n_output,), jnp.float32),
        }}

    def apply(self, params, x, ctx):
        st = ctx.get_state(self)
        axes = tuple(i for i in range(x.ndim) if i != self.channel_axis)
        if ctx.training and self.sync_axis is None:
            # fast path: custom-vjp BN (2-pass hand-fused backward)
            if self.affine:
                p = self.own(params)
                gamma = p["weight"].astype(jnp.float32)
                beta = p["bias"].astype(jnp.float32)
            else:
                gamma = jnp.ones((x.shape[self.channel_axis],), jnp.float32)
                beta = jnp.zeros((x.shape[self.channel_axis],), jnp.float32)
            y, mean, var = _bn_train(x, gamma, beta, self.channel_axis,
                                     self.eps)
            self._update_running(ctx, st, mean, var, x)
            return y
        if ctx.training:
            # sync BN: pmean the RAW moments (mean, E[x^2]) over the mesh
            # axis, then form the variance — pmean'ing per-shard variances
            # would drop the variance of the shard means and understate the
            # global variance.  Autodiff backward (the collective must
            # appear in the grad graph too).
            mean = jnp.mean(x, axis=axes, dtype=jnp.float32)
            m2 = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axes)
            mean = lax.pmean(mean, self.sync_axis)
            m2 = lax.pmean(m2, self.sync_axis)
            var = jnp.maximum(m2 - jnp.square(mean), 0.0)
            self._update_running(ctx, st, mean, var, x)
        else:
            mean, var = st["running_mean"], st["running_var"]
        shape = [1] * x.ndim
        shape[self.channel_axis] = x.shape[self.channel_axis]
        inv = lax.rsqrt(var + self.eps)
        scale, shift = inv, -mean * inv
        if self.affine:
            p = self.own(params)
            scale = scale * p["weight"]
            shift = shift * p["weight"] + p["bias"]
        return (x * scale.reshape(shape).astype(x.dtype)
                + shift.reshape(shape).astype(x.dtype))

    def _update_running(self, ctx, st, mean, var, x):
        m = self.momentum
        n = x.size // x.shape[self.channel_axis]
        if self.sync_axis is not None and ctx.training:
            n = n * lax.psum(1, self.sync_axis)  # global batch count
        unbiased = var * n / max(n - 1, 1) if isinstance(n, int) \
            else var * n / jnp.maximum(n - 1, 1)
        ctx.put_state(self, {
            "running_mean": (1 - m) * st["running_mean"]
            + m * lax.stop_gradient(mean),
            "running_var": (1 - m) * st["running_var"]
            + m * lax.stop_gradient(unbiased),
        })


class TemporalBatchNormalization(BatchNormalization):
    """Per-feature BN over (B, T, C) channels-last sequences (stats over
    batch and time).  No direct reference twin — the keras-2 converter
    needs it for Conv1D -> BatchNormalization(axis=-1) stacks; the math
    is BatchNormalization with the channel axis last."""

    channel_axis = 2


class SpatialBatchNormalization(BatchNormalization):
    """nn/SpatialBatchNormalization.scala — BN over NCHW (or NHWC with
    format='NHWC'), per-channel."""

    def __init__(self, n_output, eps=1e-5, momentum=0.1, affine=True,
                 sync_axis=None, format="NCHW", name=None):
        super().__init__(n_output, eps=eps, momentum=momentum, affine=affine,
                         sync_axis=sync_axis, name=name)
        if format == "NHWC":
            self.channel_axis = 3


class LayerNormalization(Module):
    """Per-sample last-dim layer norm (TPU-era addition used by the
    transformer flagship; reference's keras layer set has no LN)."""

    def __init__(self, hidden_size, eps=1e-5, name=None):
        super().__init__(name=name)
        self.hidden_size = hidden_size
        self.eps = eps

    def init(self, rng):
        return {self.name: {
            "weight": jnp.ones((self.hidden_size,), jnp.float32),
            "bias": jnp.zeros((self.hidden_size,), jnp.float32),
        }}

    def apply(self, params, x, ctx):
        p = self.own(params)
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mean) * lax.rsqrt(var + self.eps)
        return (y * p["weight"] + p["bias"]).astype(x.dtype)


class RMSNorm(Module):
    """RMS norm (TPU-era addition for the transformer flagship)."""

    def __init__(self, hidden_size, eps=1e-6, name=None):
        super().__init__(name=name)
        self.hidden_size = hidden_size
        self.eps = eps

    def init(self, rng):
        return {self.name: {"weight": jnp.ones((self.hidden_size,), jnp.float32)}}

    def apply(self, params, x, ctx):
        p = self.own(params)
        xf = x.astype(jnp.float32)
        y = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + self.eps)
        return (y * p["weight"]).astype(x.dtype)


class SpatialCrossMapLRN(Module):
    """Across-channel local response normalization (nn/SpatialCrossMapLRN.scala):
    y = x / (k + alpha/size * sum_{nearby channels} x^2)^beta.

    Implemented as a reduce_window over the channel dim (no loops).
    """

    def __init__(self, size=5, alpha=1.0, beta=0.75, k=1.0, format="NCHW",
                 name=None):
        super().__init__(name=name)
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.format = format

    def apply(self, params, x, ctx):
        c_ax = 1 if self.format == "NCHW" else 3
        sq = x * x
        window = [1] * x.ndim
        window[c_ax] = self.size
        lo = (self.size - 1) // 2
        hi = self.size - 1 - lo
        pads = [(0, 0)] * x.ndim
        pads[c_ax] = (lo, hi)
        s = lax.reduce_window(sq, 0.0, lax.add, tuple(window),
                              (1,) * x.ndim, pads)
        denom = (self.k + self.alpha / self.size * s) ** self.beta
        return x / denom


class SpatialWithinChannelLRN(Module):
    """Within-channel LRN over a spatial window (nn/SpatialWithinChannelLRN.scala)."""

    def __init__(self, size=5, alpha=1.0, beta=0.75, name=None):
        super().__init__(name=name)
        self.size = size
        self.alpha = alpha
        self.beta = beta

    def apply(self, params, x, ctx):
        lo = (self.size - 1) // 2
        hi = self.size - 1 - lo
        s = lax.reduce_window(
            x * x, 0.0, lax.add, (1, 1, self.size, self.size), (1, 1, 1, 1),
            [(0, 0), (0, 0), (lo, hi), (lo, hi)])
        denom = (1.0 + self.alpha / (self.size * self.size) * s) ** self.beta
        return x / denom


def _gaussian_kernel(size):
    """The reference uses a provided or default gaussian kernel for the
    *Normalization layers; default here is a normalized 2D gaussian."""
    ax = np.arange(size) - (size - 1) / 2.0
    sigma = size / 4.0
    k1 = np.exp(-(ax ** 2) / (2 * sigma ** 2))
    k2 = np.outer(k1, k1)
    return jnp.asarray((k2 / k2.sum()).astype(np.float32))


class SpatialSubtractiveNormalization(Module):
    """Subtract a weighted local mean (nn/SpatialSubtractiveNormalization.scala)."""

    def __init__(self, n_input_plane=1, kernel=None, name=None):
        super().__init__(name=name)
        self.n_input_plane = n_input_plane
        self.kernel = kernel if kernel is not None else _gaussian_kernel(9)

    def _local_mean(self, x):
        k = jnp.asarray(self.kernel, x.dtype)
        if k.ndim == 1:
            k = jnp.outer(k, k) / jnp.sum(k) ** 2
        else:
            k = k / jnp.sum(k)
        kh, kw = k.shape
        w = jnp.broadcast_to(k, (self.n_input_plane, 1, kh, kw))
        pads = [((kh - 1) // 2, kh - 1 - (kh - 1) // 2),
                ((kw - 1) // 2, kw - 1 - (kw - 1) // 2)]
        mean = lax.conv_general_dilated(
            x, w, (1, 1), pads,
            feature_group_count=self.n_input_plane,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        mean = jnp.mean(mean, axis=1, keepdims=True)
        # edge coefficient correction (reference divides by conv of ones)
        ones = jnp.ones_like(x[:1, :1])
        coef = lax.conv_general_dilated(
            ones, jnp.broadcast_to(k, (1, 1, kh, kw)), (1, 1), pads,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return mean / coef

    def apply(self, params, x, ctx):
        return x - self._local_mean(x)


class SpatialDivisiveNormalization(Module):
    """Divide by local std estimate (nn/SpatialDivisiveNormalization.scala)."""

    def __init__(self, n_input_plane=1, kernel=None, threshold=1e-4,
                 thresval=1e-4, name=None):
        super().__init__(name=name)
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel,
                                                   name=f"{self.name}_sub")
        self.threshold = threshold
        self.thresval = thresval

    def apply(self, params, x, ctx):
        local_sd = jnp.sqrt(jnp.maximum(self.sub._local_mean(x * x), 0.0))
        mean_sd = jnp.mean(local_sd, axis=(1, 2, 3), keepdims=True)
        denom = jnp.maximum(local_sd, mean_sd)
        denom = jnp.where(denom > self.threshold, denom, self.thresval)
        return x / denom


class SpatialContrastiveNormalization(Module):
    """Subtractive then divisive normalization
    (nn/SpatialContrastiveNormalization.scala)."""

    def __init__(self, n_input_plane=1, kernel=None, threshold=1e-4,
                 thresval=1e-4, name=None):
        super().__init__(name=name)
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel,
                                                   name=f"{self.name}_s")
        self.div = SpatialDivisiveNormalization(n_input_plane, kernel,
                                                threshold, thresval,
                                                name=f"{self.name}_d")

    def apply(self, params, x, ctx):
        return self.div.apply(params, self.sub.apply(params, x, ctx), ctx)


class Normalize(Module):
    """Lp-normalize over the feature dim (nn/Normalize.scala)."""

    def __init__(self, p=2.0, eps=1e-10, name=None):
        super().__init__(name=name)
        self.p = p
        self.eps = eps

    def apply(self, params, x, ctx):
        if np.isinf(self.p):
            norm = jnp.max(jnp.abs(x), axis=1, keepdims=True)
        else:
            norm = jnp.sum(jnp.abs(x) ** self.p, axis=1,
                           keepdims=True) ** (1.0 / self.p)
        return x / (norm + self.eps)


class NormalizeScale(Module):
    """L2-normalize then scale by a learned per-channel weight
    (nn/NormalizeScale.scala, used by SSD)."""

    def __init__(self, p=2.0, eps=1e-10, scale=1.0, size=None,
                 w_regularizer=None, name=None):
        super().__init__(name=name)
        self.norm = Normalize(p, eps, name=f"{self.name}_n")
        self.scale = scale
        self.size = tuple(size) if size is not None else None
        self.w_regularizer = w_regularizer

    def init(self, rng):
        size = self.size or (1,)
        return {self.name: {"weight": jnp.full(size, self.scale, jnp.float32)}}

    def apply(self, params, x, ctx):
        y = self.norm.apply(params, x, ctx)
        return y * self.own(params)["weight"].astype(x.dtype)
