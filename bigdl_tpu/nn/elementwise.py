"""Elementwise math and small parameterized utility layers.

Reference files: nn/Abs.scala, AddConstant.scala, MulConstant.scala, Exp.scala,
Log.scala, Sqrt.scala, Square.scala, Power.scala, Highway.scala, Scale.scala,
L1Penalty.scala, ActivityRegularization.scala, NegativeEntropyPenalty.scala,
nn/tf/Log1p.scala.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .module import Module
from ..utils.table import as_list


class Abs(Module):
    """|x| (nn/Abs.scala)."""
    def apply(self, params, x, ctx):
        return jnp.abs(x)


class AddConstant(Module):
    """x + constant_scalar (nn/AddConstant.scala)."""
    def __init__(self, constant_scalar, inplace=False, name=None):
        super().__init__(name=name)
        self.constant = constant_scalar

    def apply(self, params, x, ctx):
        return x + self.constant


class MulConstant(Module):
    """x * constant_scalar (nn/MulConstant.scala)."""
    def __init__(self, scalar, inplace=False, name=None):
        super().__init__(name=name)
        self.scalar = scalar

    def apply(self, params, x, ctx):
        return x * self.scalar


class Exp(Module):
    """exp(x) (nn/Exp.scala)."""
    def apply(self, params, x, ctx):
        return jnp.exp(x)


class Log(Module):
    """log(x) (nn/Log.scala)."""
    def apply(self, params, x, ctx):
        return jnp.log(x)


class Log1p(Module):
    """log(1 + x) (nn/Log1p.scala)."""
    def apply(self, params, x, ctx):
        return jnp.log1p(x)


class Sqrt(Module):
    """sqrt(x) (nn/Sqrt.scala)."""
    def apply(self, params, x, ctx):
        return jnp.sqrt(x)


class Square(Module):
    """x^2 (nn/Square.scala)."""
    def apply(self, params, x, ctx):
        return x * x


class Power(Module):
    """(shift + scale * x)^power (nn/Power.scala)."""

    def __init__(self, power, scale=1.0, shift=0.0, name=None):
        super().__init__(name=name)
        self.power = power
        self.scale = scale
        self.shift = shift

    def apply(self, params, x, ctx):
        return (self.shift + self.scale * x) ** self.power


class Highway(Module):
    """Highway network layer: t*g(Wx) + (1-t)*x (nn/Highway.scala)."""

    def __init__(self, size, with_bias=True, activation=None,
                 w_regularizer=None, b_regularizer=None, name=None):
        super().__init__(name=name)
        from .linear import Linear
        from .activation import Tanh
        self.size = size
        self.gate = Linear(size, size, with_bias=with_bias,
                           w_regularizer=w_regularizer,
                           b_regularizer=b_regularizer,
                           name=f"{self.name}_gate")
        self.transform = Linear(size, size, with_bias=with_bias,
                                w_regularizer=w_regularizer,
                                b_regularizer=b_regularizer,
                                name=f"{self.name}_transform")
        self.activation = activation or Tanh(name=f"{self.name}_act")

    def children(self):
        return [self.gate, self.transform, self.activation]

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        p = {}
        p.update(self.gate.init(k1))
        p.update(self.transform.init(k2))
        return p

    def apply(self, params, x, ctx):
        t = jax.nn.sigmoid(self.gate.apply(params, x, ctx))
        h = self.activation.apply(params, self.transform.apply(params, x, ctx),
                                  ctx)
        return t * h + (1.0 - t) * x


class Scale(Module):
    """CMul then CAdd with broadcastable size (nn/Scale.scala)."""

    def __init__(self, size, name=None):
        super().__init__(name=name)
        from .linear import CMul, CAdd
        self.cmul = CMul(size, name=f"{self.name}_mul")
        self.cadd = CAdd(size, name=f"{self.name}_add")

    def children(self):
        return [self.cmul, self.cadd]

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        p = {}
        p.update(self.cmul.init(k1))
        p.update(self.cadd.init(k2))
        return p

    def apply(self, params, x, ctx):
        return self.cadd.apply(params, self.cmul.apply(params, x, ctx), ctx)


class L1Penalty(Module):
    """Identity forward; adds l1weight * |x| to the loss via ctx side losses
    (nn/L1Penalty.scala — reference adds the penalty in the backward pass;
    here it's an explicit side loss consumed by the Optimizer)."""

    def __init__(self, l1weight, size_average=False, provide_output=True,
                 name=None):
        super().__init__(name=name)
        self.l1weight = l1weight
        self.size_average = size_average

    def apply(self, params, x, ctx):
        pen = jnp.sum(jnp.abs(x))
        if self.size_average:
            pen = pen / x.size
        ctx.add_loss(self.l1weight * pen)
        return x


class ActivityRegularization(Module):
    """l1/l2 activity penalty as a side loss (nn/ActivityRegularization.scala)."""

    def __init__(self, l1=0.0, l2=0.0, name=None):
        super().__init__(name=name)
        self.l1 = l1
        self.l2 = l2

    def apply(self, params, x, ctx):
        pen = self.l1 * jnp.sum(jnp.abs(x)) + self.l2 * jnp.sum(x * x)
        ctx.add_loss(pen)
        return x


class NegativeEntropyPenalty(Module):
    """Penalize -H(p) to encourage exploration (nn/NegativeEntropyPenalty.scala)."""

    def __init__(self, beta=0.01, name=None):
        super().__init__(name=name)
        self.beta = beta

    def apply(self, params, x, ctx):
        ent = -jnp.sum(x * jnp.log(jnp.maximum(x, 1e-8)))
        ctx.add_loss(-self.beta * ent)
        return x
