"""Graph container (≙ nn/Graph.scala, StaticGraph.scala, Input.scala,
utils/DirectedGraph.scala).

Usage mirrors the reference:

    inp = Input()
    fc1 = Linear(10, 20).inputs(inp)
    out = ReLU().inputs(fc1)
    model = Graph(inp, out)

``Module.inputs(*nodes)`` wraps the module in a :class:`Node` and records the
edges.  ``Graph.apply`` evaluates nodes in topological order at trace time —
XLA sees one static graph (the reference's DynamicGraph scheduler is
unnecessary: control flow inside jit must be static anyway; DATA-
dependent loops/branches are first-class via ``nn.WhileLoop`` /
``nn.Cond`` — nn/control_flow.py — which compile to ``lax.while_loop``
/ ``lax.cond`` inside the same program).
"""
from __future__ import annotations

from typing import List, Optional

import jax

from .module import Module
from ..utils.table import Table, as_list


class Node:
    """DAG node ref produced by ``module.inputs(...)`` (utils/Node.scala);
    Graph topo-sorts these at trace time."""
    def __init__(self, module: Optional[Module], prev_nodes: List["Node"]):
        self.module = module
        self.prev_nodes = list(prev_nodes)

    @property
    def name(self):
        return self.module.name if self.module else "input"

    def __repr__(self):
        return f"Node({self.name})"


def Input(name=None):
    """Placeholder node (nn/Input.scala)."""
    return Node(None, [])


def _inputs(self, *nodes):
    flat = []
    for n in nodes:
        if isinstance(n, (list, tuple)):
            flat.extend(n)
        else:
            flat.append(n)
    return Node(self, flat)


# attach to Module so every layer supports the reference's `.inputs(...)` API
Module.inputs = _inputs


class Graph(Module):
    """Static DAG of modules (nn/StaticGraph.scala)."""

    def __init__(self, input, output, name=None):
        super().__init__(name=name)
        self.input_nodes = input if isinstance(input, (list, tuple)) else [input]
        self.output_nodes = output if isinstance(output, (list, tuple)) else [output]
        self._topo = self._topsort()

    def _topsort(self):
        order, seen, visiting = [], set(), set()

        def visit(n):
            if id(n) in seen:
                return
            if id(n) in visiting:
                raise ValueError("Graph contains a cycle")
            visiting.add(id(n))
            for p in n.prev_nodes:
                visit(p)
            visiting.discard(id(n))
            seen.add(id(n))
            order.append(n)

        for out in self.output_nodes:
            visit(out)
        return order

    def children(self):
        return [n.module for n in self._topo if n.module is not None]

    def init(self, rng):
        params = {}
        for i, m in enumerate(self.children()):
            params.update(m.init(jax.random.fold_in(rng, i)))
        return params

    def initial_state(self):
        state = {}
        for m in self.children():
            state.update(m.initial_state())
        return state

    def apply(self, params, x, ctx):
        xs = as_list(x)
        if len(xs) != len(self.input_nodes):
            if len(self.input_nodes) == 1:
                xs = [x]
            else:
                raise ValueError(
                    f"Graph expects {len(self.input_nodes)} inputs, got {len(xs)}")
        values = {}
        for node, v in zip(self.input_nodes, xs):
            values[id(node)] = v
        for node in self._topo:
            if id(node) in values:
                continue
            if node.module is None:
                raise ValueError("unbound Input node")
            ins = [values[id(p)] for p in node.prev_nodes]
            arg = ins[0] if len(ins) == 1 else Table(*ins)
            values[id(node)] = node.module.apply(params, arg, ctx)
        outs = [values[id(n)] for n in self.output_nodes]
        return outs[0] if len(outs) == 1 else Table(*outs)

    def node(self, name):
        for n in self._topo:
            if n.module is not None and n.module.name == name:
                return n
        raise KeyError(name)


# DynamicGraph in the reference executes nodes lazily with a scheduler
# (nn/DynamicGraph.scala) to support data-dependent control ops.  Under XLA
# all control flow is compiled, so DynamicGraph is the same static evaluation.
DynamicGraph = Graph
