"""bigdl_tpu.nn — the layer/criterion library (≙ com.intel.analytics.bigdl.nn)."""
from .module import Module, Criterion, Ctx
from . import init
from .init import (Zeros, Ones, ConstInit, ConstInitMethod,
                   RandomUniform, RandomNormal,
                   Xavier, MsraFiller, BilinearFiller)
from .containers import (Container, Sequential, Concat, ConcatTable,
                         ParallelTable, MapTable, Bottle, Identity, Echo,
                         Remat)
from .graph import Graph, DynamicGraph, Input, Node
from .linear import (Linear, Bilinear, CMul, CAdd, Add, Mul, Cosine,
                     Euclidean, LookupTable, Maxout)
from .activation import (ReLU, ReLU6, Tanh, Sigmoid, ELU, LeakyReLU, PReLU,
                         RReLU, SReLU, SoftMax, SoftMin, LogSoftMax,
                         LogSigmoid, SoftPlus, SoftSign, HardTanh, Clamp,
                         HardSigmoid, HardShrink, SoftShrink, TanhShrink,
                         Threshold, BinaryThreshold, GELU, SiLU)
from .conv import (SpatialConvolution, SpatialShareConvolution,
                   SpaceToDepthConvolution,
                   SpatialDilatedConvolution, SpatialFullConvolution,
                   SpatialSeparableConvolution, TemporalConvolution,
                   VolumetricConvolution, VolumetricFullConvolution,
                   LocallyConnected1D, LocallyConnected2D,
                   SpatialConvolutionMap)
from .pooling import (SpatialMaxPooling, SpatialAveragePooling,
                      VolumetricMaxPooling, VolumetricAveragePooling,
                      TemporalMaxPooling, UpSampling1D, UpSampling2D,
                      UpSampling3D, ResizeBilinear)
from .normalization import (BatchNormalization, SpatialBatchNormalization,
                            TemporalBatchNormalization,
                            LayerNormalization, RMSNorm, SpatialCrossMapLRN,
                            SpatialWithinChannelLRN,
                            SpatialSubtractiveNormalization,
                            SpatialDivisiveNormalization,
                            SpatialContrastiveNormalization, Normalize,
                            NormalizeScale)
from .dropout import (Dropout, GaussianDropout, GaussianNoise,
                      GaussianSampler, SpatialDropout1D, SpatialDropout2D,
                      SpatialDropout3D)
from .elementwise import (Abs, AddConstant, MulConstant, Exp, Log, Log1p,
                          Sqrt, Square, Power, Highway, Scale, L1Penalty,
                          ActivityRegularization, NegativeEntropyPenalty)
from .shape_ops import (Reshape, View, InferReshape, Squeeze, Unsqueeze,
                        Transpose, Select, Narrow, Replicate, Padding,
                        SpatialZeroPadding, Cropping2D, Cropping3D,
                        Contiguous, Index, Tile, Pack, Reverse, Masking,
                        Sum, Max, Min, Mean, Negative, GradientReversal,
                        SplitAndSelect, StrideSlice)
from .table_ops import (CAddTable, CSubTable, CMulTable, CDivTable,
                        CMaxTable, CMinTable, CAveTable, JoinTable,
                        SplitTable, BifurcateSplitTable, NarrowTable,
                        SelectTable, FlattenTable, MixtureTable, DotProduct,
                        MM, MV, CosineDistance, PairwiseDistance,
                        CrossProduct, DenseToSparse, MaskedSelect)
from .recurrent import (Cell, RnnCell, LSTM, LSTMPeephole, GRU,
                        ConvLSTMPeephole, ConvLSTMPeephole3D, MultiRNNCell,
                        Recurrent, BiRecurrent, RecurrentDecoder,
                        TimeDistributed, BatchNormParams)
from .sparse import SparseLinear, LookupTableSparse, SparseJoinTable
from .tree import TreeLSTM, BinaryTreeLSTM
from .moe import SwitchFFN
from .detection import (Anchor, PriorBox, Nms, Proposal, RoiPooling,
                        DetectionOutputSSD, DetectionOutputFrcnn)
from .criterion import (ClassNLLCriterion, CrossEntropyCriterion,
                        CategoricalCrossEntropy, SoftmaxWithCriterion,
                        MSECriterion, AbsCriterion, BCECriterion,
                        SmoothL1Criterion, SmoothL1CriterionWithWeights,
                        MarginCriterion, MarginRankingCriterion,
                        HingeEmbeddingCriterion, L1HingeEmbeddingCriterion,
                        CosineEmbeddingCriterion, CosineDistanceCriterion,
                        CosineProximityCriterion, DistKLDivCriterion,
                        KLDCriterion, GaussianCriterion,
                        KullbackLeiblerDivergenceCriterion, PoissonCriterion,
                        MeanAbsolutePercentageCriterion,
                        MeanSquaredLogarithmicCriterion,
                        MultiLabelMarginCriterion,
                        MultiLabelSoftMarginCriterion, MultiMarginCriterion,
                        SoftMarginCriterion, ClassSimplexCriterion,
                        DiceCoefficientCriterion, L1Cost, DotProductCriterion,
                        PGCriterion, MultiCriterion, ParallelCriterion,
                        TimeDistributedCriterion, TimeDistributedMaskCriterion,
                        TransformerCriterion)
from . import ops
# reference-name aliases (≙ nn/StaticGraph.scala, DynamicContainer.scala,
# RNN.scala, InitializationMethod.scala): same concepts, bigdl_tpu names
from .graph import Graph as StaticGraph
from .containers import Container as DynamicContainer
from .recurrent import RnnCell as RNN
from .init import InitializationMethod
# pyspark-API compatibility spellings (bigdl/nn/layer.py: Layer is the
# module base, Model the functional-graph container)
from .module import Module as Layer
from .graph import Graph as Model

from .fusion import fold_batchnorm  # noqa: F401,E402
from .control_flow import WhileLoop, Cond  # noqa: F401,E402
