"""Dense / parameterized elementwise layers.

Reference files: nn/Linear.scala, Bilinear.scala, CMul.scala, CAdd.scala,
Add.scala, Mul.scala, Cosine.scala, Euclidean.scala, LookupTable.scala.

All matmuls go through jnp.dot / einsum so XLA tiles them onto the MXU.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .module import Module
from .init import Xavier, RandomUniform, Zeros, init_tensor
from ..utils.table import as_list


class Linear(Module):
    """y = x @ W^T + b; weight shape (out, in) as in nn/Linear.scala."""

    def __init__(self, input_size, output_size, with_bias=True,
                 w_regularizer=None, b_regularizer=None, name=None):
        super().__init__(name=name)
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        w = init_tensor(self, k1, (self.output_size, self.input_size),
                        self.input_size, self.output_size, Xavier())
        p = {"weight": w}
        if self.with_bias:
            p["bias"] = init_tensor(self, k2, (self.output_size,),
                                    self.input_size, self.output_size,
                                    Zeros(), kind="bias")
        return {self.name: p}

    def apply(self, params, x, ctx):
        p = self.own(params)
        y = jnp.dot(x, p["weight"].T.astype(x.dtype))
        if self.with_bias:
            y = y + p["bias"].astype(x.dtype)
        return y


class Bilinear(Module):
    """y_k = x1 @ W_k @ x2 + b_k over a table input {x1, x2} (nn/Bilinear.scala)."""

    def __init__(self, input_size1, input_size2, output_size, bias_res=True,
                 w_regularizer=None, b_regularizer=None, name=None):
        super().__init__(name=name)
        self.input_size1 = input_size1
        self.input_size2 = input_size2
        self.output_size = output_size
        self.bias_res = bias_res
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        fan_in = self.input_size1 * self.input_size2
        w = init_tensor(self, k1,
                        (self.output_size, self.input_size1, self.input_size2),
                        fan_in, self.output_size, RandomUniform())
        p = {"weight": w}
        if self.bias_res:
            p["bias"] = init_tensor(self, k2, (self.output_size,),
                                    fan_in, self.output_size,
                                    RandomUniform(), kind="bias")
        return {self.name: p}

    def apply(self, params, x, ctx):
        x1, x2 = as_list(x)
        p = self.own(params)
        w = p["weight"].astype(x1.dtype)
        y = jnp.einsum("bi,oij,bj->bo", x1, w, x2)
        if self.bias_res:
            y = y + p["bias"].astype(x1.dtype)
        return y


class CMul(Module):
    """Componentwise multiply by a learned tensor, broadcasting (nn/CMul.scala)."""

    def __init__(self, size, name=None):
        super().__init__(name=name)
        self.size = tuple(size)

    def init(self, rng):
        n = int(np.prod(self.size))
        w = init_tensor(self, rng, self.size, n, n, RandomUniform())
        return {self.name: {"weight": w}}

    def apply(self, params, x, ctx):
        w = self.own(params)["weight"].astype(x.dtype)
        return x * w


class CAdd(Module):
    """Componentwise add of a learned tensor, broadcasting (nn/CAdd.scala)."""

    def __init__(self, size, b_regularizer=None, name=None):
        super().__init__(name=name)
        self.size = tuple(size)
        self.b_regularizer = b_regularizer

    def init(self, rng):
        n = int(np.prod(self.size))
        b = init_tensor(self, rng, self.size, n, n, RandomUniform(), kind="bias")
        return {self.name: {"bias": b}}

    def apply(self, params, x, ctx):
        return x + self.own(params)["bias"].astype(x.dtype)


class Add(Module):
    """Learned per-feature bias vector (nn/Add.scala)."""

    def __init__(self, input_size, name=None):
        super().__init__(name=name)
        self.input_size = input_size

    def init(self, rng):
        b = init_tensor(self, rng, (self.input_size,), self.input_size,
                        self.input_size, RandomUniform(), kind="bias")
        return {self.name: {"bias": b}}

    def apply(self, params, x, ctx):
        return x + self.own(params)["bias"].astype(x.dtype)


class Mul(Module):
    """Single learned scalar gain (nn/Mul.scala)."""

    def init(self, rng):
        w = init_tensor(self, rng, (1,), 1, 1, RandomUniform())
        return {self.name: {"weight": w}}

    def apply(self, params, x, ctx):
        return x * self.own(params)["weight"].astype(x.dtype)


class Cosine(Module):
    """Cosine similarity of the input with each of `output_size` learned
    weight rows (nn/Cosine.scala)."""

    def __init__(self, input_size, output_size, name=None):
        super().__init__(name=name)
        self.input_size = input_size
        self.output_size = output_size

    def init(self, rng):
        w = init_tensor(self, rng, (self.output_size, self.input_size),
                        self.input_size, self.output_size, RandomUniform())
        return {self.name: {"weight": w}}

    def apply(self, params, x, ctx):
        w = self.own(params)["weight"].astype(x.dtype)
        xn = x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-12)
        wn = w / jnp.maximum(jnp.linalg.norm(w, axis=-1, keepdims=True), 1e-12)
        return jnp.dot(xn, wn.T)


class Euclidean(Module):
    """Euclidean distance of the input to `output_size` learned centers
    (nn/Euclidean.scala). Weight shape (in, out) as in the reference."""

    def __init__(self, input_size, output_size, fast_backward=True, name=None):
        super().__init__(name=name)
        self.input_size = input_size
        self.output_size = output_size

    def init(self, rng):
        w = init_tensor(self, rng, (self.input_size, self.output_size),
                        self.input_size, self.output_size, RandomUniform())
        return {self.name: {"weight": w}}

    def apply(self, params, x, ctx):
        w = self.own(params)["weight"].astype(x.dtype)
        diff = x[..., :, None] - w[None, :, :]
        return jnp.sqrt(jnp.sum(diff * diff, axis=-2) + 1e-12)


class LookupTable(Module):
    """Embedding lookup (nn/LookupTable.scala). Indices are 1-based (Torch
    convention); `padding_value` rows embed to zero when masked.

    On TPU this is a one-gather op; max_norm renormalization is applied
    functionally to the gathered rows (reference renorms in-place pre-lookup,
    same result for the looked-up rows).
    """

    def __init__(self, n_index, n_output, padding_value=0.0,
                 max_norm=None, norm_type=2.0, w_regularizer=None,
                 mask_zero=False, name=None):
        super().__init__(name=name)
        self.n_index = n_index
        self.n_output = n_output
        self.padding_value = padding_value
        self.max_norm = max_norm
        self.norm_type = norm_type
        self.w_regularizer = w_regularizer
        self.mask_zero = mask_zero

    def init(self, rng):
        from .init import RandomNormal
        w = init_tensor(self, rng, (self.n_index, self.n_output),
                        self.n_index, self.n_output, RandomNormal(0, 1))
        return {self.name: {"weight": w}}

    def apply(self, params, x, ctx):
        w = self.own(params)["weight"]
        idx = x.astype(jnp.int32) - 1  # 1-based -> 0-based
        idx_c = jnp.clip(idx, 0, self.n_index - 1)
        out = jnp.take(w, idx_c, axis=0)
        if self.max_norm is not None:
            norms = jnp.linalg.norm(out, ord=self.norm_type, axis=-1,
                                    keepdims=True)
            scale = jnp.minimum(1.0, self.max_norm / jnp.maximum(norms, 1e-7))
            out = out * scale
        if self.mask_zero and self.padding_value is not None:
            mask = (x.astype(jnp.int32) != int(self.padding_value))
            out = out * mask[..., None].astype(out.dtype)
        return out


class Maxout(Module):
    """Maxout unit (nn/Maxout.scala:46): Linear(in, out*m) → reshape
    (m, out) → max over m.  One MXU matmul + a reduce that XLA fuses."""

    def __init__(self, input_size, output_size, maxout_number,
                 with_bias=True, w_regularizer=None, b_regularizer=None,
                 name=None):
        super().__init__(name=name)
        self.input_size = input_size
        self.output_size = output_size
        self.maxout_number = maxout_number
        self.with_bias = with_bias
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        fan_in, fan_out = self.input_size, self.output_size
        w = init_tensor(self, k1,
                        (self.input_size,
                         self.output_size * self.maxout_number),
                        fan_in, fan_out, Xavier())
        p = {"weight": w}
        if self.with_bias:
            p["bias"] = init_tensor(
                self, k2, (self.output_size * self.maxout_number,),
                fan_in, fan_out, Zeros(), kind="bias")
        return {self.name: p}

    def apply(self, params, x, ctx):
        p = self.own(params)
        y = x @ p["weight"].astype(x.dtype)
        if self.with_bias:
            y = y + p["bias"].astype(x.dtype)
        y = y.reshape(y.shape[:-1] + (self.maxout_number, self.output_size))
        return jnp.max(y, axis=-2)
