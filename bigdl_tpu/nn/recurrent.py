"""Recurrent layers.

Reference files: nn/Cell.scala, RNN.scala (RnnCell), LSTM.scala,
LSTMPeephole.scala, GRU.scala, ConvLSTMPeephole.scala, ConvLSTMPeephole3D.scala,
MultiRNNCell.scala, Recurrent.scala, BiRecurrent.scala, RecurrentDecoder.scala,
TimeDistributed.scala.

TPU-first: the reference unrolls timesteps in a Scala while-loop over cloned
cells; here ``Recurrent`` is one ``lax.scan`` over a single compiled cell step
— one trace, weights shared by construction, full XLA fusion across the gate
matmuls (which are batched into single MXU calls per step).

Input layout is (B, T, ...) batch-first, matching the reference default.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .module import Module
from .init import Xavier, Zeros, init_tensor
from ..utils.table import Table, as_list


def _conv_out_size(in_size, k, stride, pad):
    """Spatial size after a conv; pad == -1 means SAME (ceil(in/stride))."""
    if pad == -1:
        return -(-in_size // stride)
    return (in_size + 2 * pad - k) // stride + 1


class BatchNormParams:
    """Recurrent input-projection BatchNorm config (≙ nn/Recurrent.scala:33
    BatchNormParams + Recurrent.scala:111-119: the cell's input projection
    is normalized over (batch, time) before entering the recurrence).

    ``init_weight`` / ``init_bias`` seed the affine gamma/beta."""

    def __init__(self, eps=1e-5, momentum=0.1, affine=True,
                 init_weight=None, init_bias=None):
        self.eps = float(eps)
        self.momentum = float(momentum)
        self.affine = bool(affine)
        self.init_weight = None if init_weight is None \
            else jnp.asarray(init_weight, jnp.float32)
        self.init_bias = None if init_bias is None \
            else jnp.asarray(init_bias, jnp.float32)


class Cell(Module):
    """Base RNN cell: step(params, x_t, hidden, ctx) -> (out_t, new_hidden);
    ``zero_hidden(batch, dtype)`` builds the initial state pytree.

    Cells whose input projection is a plain matmul also expose
    ``pre_width`` / ``project_input`` / ``step_projected`` so Recurrent can
    hoist the projection out of the scan — one (B*T, in) @ (in, K) MXU call
    instead of T small ones — and slot a BatchNorm between projection and
    recurrence (≙ the reference's Cell.preTopology factoring,
    Cell.scala:50-58)."""

    #: width of the hoisted input projection, or None if unsupported
    pre_width = None

    def project_input(self, params, x):
        """(..., in) -> (..., pre_width): the input half of the gate
        pre-activations, WITHOUT bias (biases stay in step_projected /
        the Recurrent-level pre-bias)."""
        raise NotImplementedError(
            f"{type(self).__name__} has no hoistable input projection")

    def step_projected(self, params, xp, hidden, ctx):
        """step(), but taking the already-projected input ``xp``."""
        raise NotImplementedError(
            f"{type(self).__name__} has no hoistable input projection")

    def _step_key(self, ctx):
        """Per-timestep dropout key: Recurrent/RecurrentDecoder thread a
        fresh key through their scan carry (ctx.step_rng); a direct
        single-step apply falls back to the per-module key.  fold_in on
        the uid keeps stacked cells' (MultiRNNCell) masks independent."""
        key = ctx.step_rng if ctx.step_rng is not None else ctx.rng(self)
        return jax.random.fold_in(key, self._uid % (2 ** 31))

    def step(self, params, x, hidden, ctx):
        raise NotImplementedError

    def zero_hidden(self, batch_size, dtype=jnp.float32):
        raise NotImplementedError

    # a cell can be applied directly to a table {x, hidden} like the reference
    def apply(self, params, x, ctx):
        xs = as_list(x)
        out, new_h = self.step(params, xs[0], xs[1], ctx)
        return Table(out, new_h)


def _gate_params(module, rng, input_size, hidden_size, n_gates):
    """Fused gate weights: one (in+hid, n_gates*hid) matmul per step."""
    k1, k2, k3 = jax.random.split(rng, 3)
    wi = init_tensor(module, k1, (input_size, n_gates * hidden_size),
                     input_size, n_gates * hidden_size, Xavier())
    wh = init_tensor(module, k2, (hidden_size, n_gates * hidden_size),
                     hidden_size, n_gates * hidden_size, Xavier())
    b = init_tensor(module, k3, (n_gates * hidden_size,), input_size,
                    n_gates * hidden_size, Zeros(), kind="bias")
    return {"weight_i": wi, "weight_h": wh, "bias": b}


def _drop(v, p, key):
    """Inverted dropout on one projection input (≙ the Dropout module
    the reference places before each cell Linear when p>0)."""
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, v.shape)
    return jnp.where(mask, v, 0).astype(v.dtype) / keep


def _gate_dropout_matmul(x, h, wi, wh, n_gates, p, key):
    """Fused-weight equivalent of the reference's per-gate
    Sequential(Dropout(p), Linear) stacks (LSTM.scala:77-96 i2g/h2g with
    p>0): each gate's input AND hidden projection sees an INDEPENDENT
    inverted-dropout mask.  Same FLOPs as the fused matmul — the (B,D)
    @ (D,G*H) product becomes a (G,B,D) x (D,G,H) einsum."""
    b_sz, d_in = x.shape
    h_in = h.shape[1]
    h_sz = wi.shape[1] // n_gates
    kx, kh = jax.random.split(key)
    keep = 1.0 - p
    mx = jax.random.bernoulli(kx, keep, (n_gates,) + x.shape)
    mh = jax.random.bernoulli(kh, keep, (n_gates,) + h.shape)
    xg = (jnp.where(mx, x[None], 0) / keep).astype(x.dtype)
    hg = (jnp.where(mh, h[None], 0) / keep).astype(x.dtype)
    zi = jnp.einsum("gbd,dgh->bgh", xg, wi.reshape(d_in, n_gates, h_sz))
    zh = jnp.einsum("gbd,dgh->bgh", hg, wh.reshape(h_in, n_gates, h_sz))
    return (zi + zh).reshape(b_sz, n_gates * h_sz)


class RnnCell(Cell):
    """Vanilla RNN cell: h' = act(W_i x + W_h h + b) (nn/RNN.scala)."""

    def __init__(self, input_size, hidden_size, activation=None,
                 isInputWithBias=True, w_regularizer=None, u_regularizer=None,
                 b_regularizer=None, name=None):
        super().__init__(name=name)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation  # Module or None -> tanh
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def init(self, rng):
        return {self.name: _gate_params(self, rng, self.input_size,
                                        self.hidden_size, 1)}

    def zero_hidden(self, batch_size, dtype=jnp.float32):
        return jnp.zeros((batch_size, self.hidden_size), dtype)

    def _act(self, v, params, ctx):
        if self.activation is None:
            return jnp.tanh(v)
        return self.activation.apply(params, v, ctx)

    @property
    def pre_width(self):
        return self.hidden_size

    def project_input(self, params, x):
        return x @ self.own(params)["weight_i"].astype(x.dtype)

    def step_projected(self, params, xp, h, ctx):
        p = self.own(params)
        z = (xp + h @ p["weight_h"].astype(xp.dtype)
             + p["bias"].astype(xp.dtype))
        h2 = self._act(z, params, ctx)
        return h2, h2

    def step(self, params, x, h, ctx):
        return self.step_projected(
            params, self.project_input(params, x), h, ctx)


class LSTM(Cell):
    """Standard LSTM cell (nn/LSTM.scala). Gate order i, f, g(cell), o.
    Hidden state is a Table {h, c}; output is h."""

    def __init__(self, input_size, hidden_size, p=0.0, activation=None,
                 inner_activation=None, w_regularizer=None, u_regularizer=None,
                 b_regularizer=None, name=None):
        super().__init__(name=name)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.dropout_p = p
        self.activation = activation
        self.inner_activation = inner_activation
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def init(self, rng):
        return {self.name: _gate_params(self, rng, self.input_size,
                                        self.hidden_size, 4)}

    def zero_hidden(self, batch_size, dtype=jnp.float32):
        return Table(jnp.zeros((batch_size, self.hidden_size), dtype),
                     jnp.zeros((batch_size, self.hidden_size), dtype))

    @property
    def pre_width(self):
        return 4 * self.hidden_size

    def project_input(self, params, x):
        return x @ self.own(params)["weight_i"].astype(x.dtype)

    def _from_z(self, params, z, hidden, ctx):
        h, c = as_list(hidden)
        i, f, g, o = jnp.split(z, 4, axis=-1)
        inner = jax.nn.sigmoid if self.inner_activation is None else \
            (lambda v: self.inner_activation.apply(params, v, ctx))
        act = jnp.tanh if self.activation is None else \
            (lambda v: self.activation.apply(params, v, ctx))
        i, f, o = inner(i), inner(f), inner(o)
        g = act(g)
        c2 = f * c + i * g
        h2 = o * act(c2)
        return h2, Table(h2, c2)

    def step_projected(self, params, xp, hidden, ctx):
        h, _ = as_list(hidden)
        p = self.own(params)
        z = (xp + h @ p["weight_h"].astype(xp.dtype)
             + p["bias"].astype(xp.dtype))
        return self._from_z(params, z, hidden, ctx)

    def step(self, params, x, hidden, ctx):
        if self.dropout_p and ctx.training:
            h, _ = as_list(hidden)
            p = self.own(params)
            z = _gate_dropout_matmul(
                x, h, p["weight_i"].astype(x.dtype),
                p["weight_h"].astype(x.dtype), 4, self.dropout_p,
                self._step_key(ctx)) + p["bias"].astype(x.dtype)
            return self._from_z(params, z, hidden, ctx)
        return self.step_projected(
            params, self.project_input(params, x), hidden, ctx)


class LSTMPeephole(Cell):
    """LSTM with peephole connections from c into the gates
    (nn/LSTMPeephole.scala)."""

    def __init__(self, input_size, hidden_size, p=0.0, w_regularizer=None,
                 u_regularizer=None, b_regularizer=None, name=None):
        super().__init__(name=name)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.dropout_p = p

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        base = _gate_params(self, k1, self.input_size, self.hidden_size, 4)
        ph = 0.1 * jax.random.normal(k2, (3, self.hidden_size), jnp.float32)
        base["peephole"] = ph
        return {self.name: base}

    def zero_hidden(self, batch_size, dtype=jnp.float32):
        return Table(jnp.zeros((batch_size, self.hidden_size), dtype),
                     jnp.zeros((batch_size, self.hidden_size), dtype))

    @property
    def pre_width(self):
        return 4 * self.hidden_size

    def project_input(self, params, x):
        return x @ self.own(params)["weight_i"].astype(x.dtype)

    def _from_z(self, params, z, hidden, ctx):
        _, c = as_list(hidden)
        p = self.own(params)
        i, f, g, o = jnp.split(z, 4, axis=-1)
        ph = p["peephole"].astype(z.dtype)
        i = jax.nn.sigmoid(i + ph[0] * c)
        f = jax.nn.sigmoid(f + ph[1] * c)
        g = jnp.tanh(g)
        c2 = f * c + i * g
        o = jax.nn.sigmoid(o + ph[2] * c2)
        h2 = o * jnp.tanh(c2)
        return h2, Table(h2, c2)

    def step_projected(self, params, xp, hidden, ctx):
        h, _ = as_list(hidden)
        p = self.own(params)
        z = (xp + h @ p["weight_h"].astype(xp.dtype)
             + p["bias"].astype(xp.dtype))
        return self._from_z(params, z, hidden, ctx)

    def step(self, params, x, hidden, ctx):
        if self.dropout_p and ctx.training:
            h, _ = as_list(hidden)
            p = self.own(params)
            z = _gate_dropout_matmul(
                x, h, p["weight_i"].astype(x.dtype),
                p["weight_h"].astype(x.dtype), 4, self.dropout_p,
                self._step_key(ctx)) + p["bias"].astype(x.dtype)
            return self._from_z(params, z, hidden, ctx)
        return self.step_projected(
            params, self.project_input(params, x), hidden, ctx)


class GRU(Cell):
    """GRU cell (nn/GRU.scala). Gate order r(reset), z(update), n(new).

    ``reset_after=True`` is the v3/CuDNN form (tf.keras 2.x default):
    the reset gate multiplies the candidate's RECURRENT contribution
    after its matmul (r * (h @ U_h + b_h)) instead of gating h before
    it, with separate input/recurrent biases.  Classic (reference)
    form is the default."""

    def __init__(self, input_size, hidden_size, p=0.0, w_regularizer=None,
                 u_regularizer=None, b_regularizer=None,
                 reset_after=False, activation=None, inner_activation=None,
                 name=None):
        super().__init__(name=name)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.reset_after = reset_after
        self.dropout_p = p
        # ≙ nn/GRU.scala:62-72 activation (candidate, default Tanh) /
        # innerActivation (r+z gates, default Sigmoid)
        self.activation = activation
        self.inner_activation = inner_activation

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        gates = _gate_params(self, k1, self.input_size, self.hidden_size, 2)
        newg = _gate_params(self, k2, self.input_size, self.hidden_size, 1)
        if self.reset_after:
            gates["bias_h"] = jnp.zeros_like(gates["bias"])
            newg["bias_h"] = jnp.zeros_like(newg["bias"])
        return {self.name: {"gates": gates, "new": newg}}

    def zero_hidden(self, batch_size, dtype=jnp.float32):
        return jnp.zeros((batch_size, self.hidden_size), dtype)

    @property
    def pre_width(self):
        return 3 * self.hidden_size

    def project_input(self, params, x):
        p = self.own(params)
        return jnp.concatenate(
            [x @ p["gates"]["weight_i"].astype(x.dtype),
             x @ p["new"]["weight_i"].astype(x.dtype)], axis=-1)

    def _tail(self, params, z2, xn, h, ctx, drop_h=None):
        """Shared post-projection math: r/z gates from ``z2``, candidate
        from its input contribution ``xn`` plus the recurrent path on
        ``h``, blend.  ``drop_h`` (p>0 training only) is the dropout
        applied to the candidate's recurrent input — h itself for
        reset_after, r*h for the classic form (GRU.scala p>0 places a
        Dropout before each cell Linear)."""
        n = self.own(params)["new"]
        inner = jax.nn.sigmoid if self.inner_activation is None else \
            (lambda v: self.inner_activation.apply(params, v, ctx))
        act = jnp.tanh if self.activation is None else \
            (lambda v: self.activation.apply(params, v, ctx))
        # split BEFORE the inner activation: the reference applies it per
        # h-wide gate after Narrow (GRU.scala buildGates), so an
        # axis-dependent activation (SoftMax) must not see the 2h concat
        r_pre, z_pre = jnp.split(z2, 2, axis=-1)
        r, z = inner(r_pre), inner(z_pre)
        dt = z2.dtype
        if self.reset_after:
            hc = drop_h(h) if drop_h is not None else h
            rec = hc @ n["weight_h"].astype(dt) + n["bias_h"].astype(dt)
            nh = act(xn + n["bias"].astype(dt) + r * rec)
        else:
            rh = r * h
            if drop_h is not None:
                rh = drop_h(rh)
            nh = act(xn + rh @ n["weight_h"].astype(dt)
                     + n["bias"].astype(dt))
        h2 = (1.0 - z) * nh + z * h
        return h2, h2

    def step_projected(self, params, xp, h, ctx):
        g = self.own(params)["gates"]
        hs = self.hidden_size
        xg, xn = xp[..., :2 * hs], xp[..., 2 * hs:]
        z2 = (xg + h @ g["weight_h"].astype(xp.dtype)
              + g["bias"].astype(xp.dtype))
        if self.reset_after:
            z2 = z2 + g["bias_h"].astype(xp.dtype)
        return self._tail(params, z2, xn, h, ctx)

    def step(self, params, x, h, ctx):
        if not (self.dropout_p and ctx.training):
            return self.step_projected(
                params, self.project_input(params, x), h, ctx)
        p = self.own(params)
        g = p["gates"]
        n = p["new"]
        k_g, k_x, k_h = jax.random.split(self._step_key(ctx), 3)
        z2 = _gate_dropout_matmul(
            x, h, g["weight_i"].astype(x.dtype),
            g["weight_h"].astype(x.dtype), 2, self.dropout_p,
            k_g) + g["bias"].astype(x.dtype)
        if self.reset_after:
            z2 = z2 + g["bias_h"].astype(x.dtype)
        xc = _drop(x, self.dropout_p, k_x)
        xn = xc @ n["weight_i"].astype(x.dtype)
        return self._tail(params, z2, xn, h, ctx,
                          drop_h=lambda v: _drop(v, self.dropout_p, k_h))


class ConvLSTMPeephole(Cell):
    """Convolutional LSTM with peepholes over NCHW maps
    (nn/ConvLSTMPeephole.scala)."""

    def __init__(self, input_size, output_size, kernel_i, kernel_c,
                 stride=1, padding=-1, activation=None, inner_activation=None,
                 w_regularizer=None, u_regularizer=None, b_regularizer=None,
                 c_regularizer=None, with_peephole=True, name=None):
        super().__init__(name=name)
        from .conv import SpatialConvolution
        self.input_size = input_size
        self.output_size = output_size
        self.with_peephole = with_peephole
        self.conv_i = SpatialConvolution(
            input_size, 4 * output_size, kernel_i, kernel_i, stride, stride,
            padding, padding, name=f"{self.name}_ci")
        # hidden conv must preserve spatial shape: stride 1, SAME padding
        self.conv_h = SpatialConvolution(
            output_size, 4 * output_size, kernel_c, kernel_c, 1, 1,
            -1, -1, with_bias=False, name=f"{self.name}_ch")

    def children(self):
        return [self.conv_i, self.conv_h]

    def init(self, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        p = {}
        p.update(self.conv_i.init(k1))
        p.update(self.conv_h.init(k2))
        if self.with_peephole:
            p[self.name] = {"peephole": 0.1 * jax.random.normal(
                k3, (3, self.output_size), jnp.float32)}
        return p

    def zero_hidden(self, batch_size, dtype=jnp.float32, spatial=None):
        if spatial is None:
            raise ValueError("ConvLSTMPeephole needs spatial dims for hidden")
        # hidden lives at conv_i's OUTPUT resolution (stride may downsample)
        out_spatial = tuple(
            _conv_out_size(s, k, st, p) for s, k, st, p in zip(
                spatial, self.conv_i.kernel, self.conv_i.stride,
                self.conv_i.pad))
        shape = (batch_size, self.output_size) + out_spatial
        return Table(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))

    def step(self, params, x, hidden, ctx):
        h, c = as_list(hidden)
        z = (self.conv_i.apply(params, x, ctx)
             + self.conv_h.apply(params, h, ctx))
        i, f, g, o = jnp.split(z, 4, axis=1)
        if self.with_peephole:
            ph = self.own(params)["peephole"].astype(x.dtype)
            i = i + ph[0][None, :, None, None] * c
            f = f + ph[1][None, :, None, None] * c
        i, f = jax.nn.sigmoid(i), jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        c2 = f * c + i * g
        if self.with_peephole:
            o = o + ph[2][None, :, None, None] * c2
        o = jax.nn.sigmoid(o)
        h2 = o * jnp.tanh(c2)
        return h2, Table(h2, c2)


class MultiRNNCell(Cell):
    """Stack of cells applied at each timestep (nn/MultiRNNCell.scala)."""

    def __init__(self, cells, name=None):
        super().__init__(name=name)
        self.cells = list(cells)

    def children(self):
        return list(self.cells)

    def init(self, rng):
        p = {}
        for i, c in enumerate(self.cells):
            p.update(c.init(jax.random.fold_in(rng, i)))
        return p

    def zero_hidden(self, batch_size, dtype=jnp.float32):
        return Table(*[c.zero_hidden(batch_size, dtype) for c in self.cells])

    def step(self, params, x, hidden, ctx):
        hs = as_list(hidden)
        new_hs = []
        out = x
        for cell, h in zip(self.cells, hs):
            out, nh = cell.step(params, out, h, ctx)
            new_hs.append(nh)
        return out, Table(*new_hs)


class Recurrent(Module):
    """Run a cell over the time dim of (B, T, ...) input via lax.scan
    (nn/Recurrent.scala).

    ``batch_norm_params`` (≙ Recurrent.scala:111-119) hoists the cell's
    input projection out of the scan and applies BatchNorm over
    (batch, time) between projection and recurrence — the pre-projection
    bias lives in a Recurrent-level ``bias_pre`` param (the reference's
    preTopology Linear bias, applied BEFORE the normalization).

    ``hoist_input=True`` hoists the projection WITHOUT BatchNorm — a
    TPU-side optimization: one (B*T, in) @ (in, K) MXU matmul replaces T
    per-step (B, in) matmuls; math is identical (same add order), only
    fp tiling may differ.

    ``mask_zero=True`` (≙ Recurrent.scala:39-49, :265-300): on 3D input,
    an all-zero (batch, time) row past the batch's minimum sequence
    length keeps the hidden state unchanged and outputs zero — padded
    variable-length batches run as one static-shape scan with a select,
    no host-side lengths needed."""

    def __init__(self, cell=None, batch_norm_params=None, hoist_input=False,
                 mask_zero=False, name=None):
        super().__init__(name=name)
        self.cell = cell
        self.batch_norm_params = batch_norm_params
        self.hoist_input = bool(hoist_input)
        self.mask_zero = bool(mask_zero)
        self.bn = None

    def add(self, cell):
        self.cell = cell
        return self

    def children(self):
        if self.cell is None:
            return []
        if self.batch_norm_params is not None and self.bn is None:
            try:
                self._ensure_bn()
            except ValueError:
                pass  # unsupported-cell error surfaces at init/apply
        out = [self.cell]
        if self.bn is not None:
            out.append(self.bn)
        return out

    def _serde_restore_children(self, children):
        if children and children[0] is not None:
            self.cell = children[0]

    def _bn_config(self):
        bp = self.batch_norm_params
        if isinstance(bp, dict):
            bp = BatchNormParams(**bp)
        return bp

    def _ensure_bn(self):
        if self.batch_norm_params is None or self.bn is not None:
            return
        if self.cell is None or getattr(self.cell, "pre_width", None) is None:
            # ≙ Recurrent.scala:104-108: BN needs a preTopology projection
            raise ValueError(
                f"{type(self.cell).__name__ if self.cell else None} does "
                "not support BatchNormParams: no hoistable input projection")
        if self._cell_is_stochastic(self.cell):
            raise ValueError(
                "BatchNormParams requires a p == 0 cell (the reference's "
                "p > 0 cells have no preTopology, Recurrent.scala:104)")
        from .normalization import TemporalBatchNormalization
        bp = self._bn_config()
        self.bn = TemporalBatchNormalization(
            self.cell.pre_width, eps=bp.eps, momentum=bp.momentum,
            affine=bp.affine, name=f"{self.name}_bn")

    def init(self, rng):
        if self.batch_norm_params is None:
            return self.cell.init(rng)
        self._ensure_bn()
        k1, k2 = jax.random.split(rng)
        p = self.cell.init(k1)
        p.update(self.bn.init(k2))
        bp = self._bn_config()
        if bp.affine and bp.init_weight is not None:
            p[self.bn.name]["weight"] = jnp.reshape(
                bp.init_weight, p[self.bn.name]["weight"].shape)
        if bp.affine and bp.init_bias is not None:
            p[self.bn.name]["bias"] = jnp.reshape(
                bp.init_bias, p[self.bn.name]["bias"].shape)
        p[self.name] = {"bias_pre": jnp.zeros((self.cell.pre_width,),
                                              jnp.float32)}
        return p

    def initial_state(self):
        st = dict(self.cell.initial_state())
        if self.batch_norm_params is not None:
            self._ensure_bn()
            st.update(self.bn.initial_state())
        return st

    def _initial_hidden(self, x):
        init = getattr(self, "_user_hidden", None)
        if init is not None:
            from jax.core import Tracer
            if isinstance(x, Tracer):
                raise ValueError(
                    f"{self.name}: set_hidden_state is a shell-only API — "
                    "this forward is being traced (jit); thread the "
                    "initial hidden state functionally instead, or "
                    "clear_hidden_state() before compiling")
            return init
        if hasattr(self.cell, "zero_hidden"):
            try:
                return self.cell.zero_hidden(x.shape[0], x.dtype)
            except (ValueError, TypeError):
                return self.cell.zero_hidden(x.shape[0], x.dtype,
                                             spatial=x.shape[3:])
        raise ValueError("cell must define zero_hidden")

    # -- stateful-decoding shell API (≙ Recurrent.scala:307-324) -------- #
    def set_hidden_state(self, hidden):
        """Seed the next SHELL forward's initial hidden state
        (≙ setHiddenState).  Pass the structure ``get_hidden_state``
        returns (e.g. Table(h, c) for LSTM).  Shell-only: a traced
        (jit) apply raises while a seed is set — compiled streaming
        loops must thread the state functionally, and a jitted program
        compiled earlier can never see a later seed."""
        self._user_hidden = hidden
        self._predictors = {}   # drop jitted predictors compiled seedless
        return self

    def clear_hidden_state(self):
        self._user_hidden = None
        self._predictors = {}
        return self

    def get_hidden_state(self):
        """Hidden state at the last timestep of the most recent SHELL
        forward (≙ getHiddenState; Recurrent.scala:309 requires a
        forward first).  A traced forward in between invalidates the
        record — stale state is an error here, never silently reused."""
        h = getattr(self, "_last_hidden", None)
        if h is None:
            raise RuntimeError(
                "get_hidden_state must be called after a (non-jit) forward")
        return h

    def _record_hidden(self, h):
        from jax.core import Tracer
        if any(isinstance(l, Tracer)
               for l in jax.tree_util.tree_leaves(h)):
            # traced forward: the carry cannot escape; also invalidate
            # any earlier record so a later get_hidden_state cannot
            # return state from the wrong (pre-jit) forward
            self._last_hidden = None
        else:
            self._last_hidden = h

    @staticmethod
    def _cell_is_stochastic(cell):
        # modules() includes the cell itself
        return any(getattr(m, "dropout_p", 0.0) for m in cell.modules())

    def _mask_seq(self, x):
        """(keep (B,T) bool, skip (T,B) bool) for mask_zero, else None.
        ≙ Recurrent.scala:265-270: a row is padding when its |max| is 0,
        and masking only applies past the batch's minimum length (rows
        before that are processed normally, zeros included)."""
        if not self.mask_zero:
            return None
        if x.ndim != 3:
            raise ValueError(
                f"{self.name}: mask_zero needs 3D (batch, time, dim) "
                "input (≙ Recurrent.scala:266 require)")
        keep = jnp.any(x != 0, axis=-1)                       # (B, T)
        min_len = jnp.min(jnp.sum(keep, axis=1))
        t_idx = jnp.arange(x.shape[1])
        skip = (~keep) & (t_idx >= min_len)[None, :]          # (B, T)
        return keep, jnp.swapaxes(skip, 0, 1)                 # skip: (T, B)

    @staticmethod
    def _masked(skip_t, out, h2, h):
        """Frozen state + zero output for skipped rows."""
        h2 = jax.tree_util.tree_map(
            lambda new, old: jnp.where(skip_t[:, None], old, new), h2, h)
        return jnp.where(skip_t[:, None], 0, out), h2

    def apply(self, params, x, ctx):
        hidden0 = self._initial_hidden(x)
        mask = self._mask_seq(x)

        # bn mode ALWAYS hoists (_ensure_bn rejects stochastic cells);
        # bare hoist_input falls back when it can't (stochastic cell in
        # training, or a cell with no separable projection)
        hoist = self.batch_norm_params is not None or (
            self.hoist_input
            and getattr(self.cell, "pre_width", None) is not None
            and not (ctx.training and self._cell_is_stochastic(self.cell)))
        if hoist:
            self._ensure_bn()
            proj = self.cell.project_input(params, x)  # (B, T, K)
            if self.bn is not None:
                proj = proj + self.own(params)["bias_pre"].astype(proj.dtype)
                if mask is not None:
                    # ≙ TimeDistributed(pre, maskZero) inside Recurrent:
                    # padded rows enter the BN (and its batch stats) as
                    # exact zeros (Recurrent.scala:101)
                    proj = jnp.where(mask[0][..., None], proj, 0)
                proj = self.bn.apply(params, proj, ctx)

            if mask is None:
                def body(h, xp_t):
                    out, h2 = self.cell.step_projected(params, xp_t, h, ctx)
                    return h2, out

                h_fin, outs = lax.scan(body, hidden0,
                                       jnp.swapaxes(proj, 0, 1))
            else:
                def body(h, inp):
                    xp_t, skip_t = inp
                    out, h2 = self.cell.step_projected(params, xp_t, h, ctx)
                    out, h2 = self._masked(skip_t, out, h2, h)
                    return h2, out

                h_fin, outs = lax.scan(body, hidden0,
                                       (jnp.swapaxes(proj, 0, 1), mask[1]))
            self._record_hidden(h_fin)
            return jnp.swapaxes(outs, 0, 1)

        xs_t = jnp.swapaxes(x, 0, 1)  # (T, B, ...)

        if ctx.training and ctx.rng_key is not None \
                and self._cell_is_stochastic(self.cell):
            # stochastic cell (p>0): thread a fresh key through the scan
            # carry so every timestep draws INDEPENDENT dropout masks
            # (≙ the reference's Dropout re-sampling per forward call)
            def body(carry, inp):
                x_t, skip_t = inp
                h, key = carry
                key, sub = jax.random.split(key)
                ctx.step_rng = sub
                out, h2 = self.cell.step(params, x_t, h, ctx)
                if skip_t is not None:
                    out, h2 = self._masked(skip_t, out, h2, h)
                return (h2, key), out

            carry, outs = lax.scan(
                body, (hidden0, ctx.rng(self)),
                (xs_t, mask[1] if mask is not None else None))
            ctx.step_rng = None
            self._record_hidden(carry[0])
            return jnp.swapaxes(outs, 0, 1)

        def body(h, inp):
            x_t, skip_t = inp
            out, h2 = self.cell.step(params, x_t, h, ctx)
            if skip_t is not None:
                out, h2 = self._masked(skip_t, out, h2, h)
            return h2, out

        h_fin, outs = lax.scan(body, hidden0,
                               (xs_t, mask[1] if mask is not None else None))
        self._record_hidden(h_fin)
        return jnp.swapaxes(outs, 0, 1)


class BiRecurrent(Module):
    """Bidirectional recurrence; merge defaults to elementwise add
    (nn/BiRecurrent.scala:65 — CAddTable).

    ``is_split_input=True`` halves the FEATURE dim instead of duplicating
    the input: first half to the forward RNN, second half to the backward
    one (≙ BiRecurrent.scala:50-52 BifurcateSplitTable(featDim)); the
    cell's input_size must then be half the model feature width."""

    def __init__(self, merge=None, cell=None, is_split_input=False,
                 batch_norm_params=None, name=None):
        super().__init__(name=name)
        self.merge = merge
        self.fwd_cell = cell
        self.bwd_cell = None
        self.is_split_input = is_split_input
        # each direction gets its OWN BatchNorm instance, exactly like the
        # reference's layer/revLayer = Recurrent(batchNormParams) pair
        # (BiRecurrent.scala:45-46)
        self.batch_norm_params = batch_norm_params

    def add(self, cell):
        self.fwd_cell = cell
        # drop any derived backward copy of the OLD cell (children()/
        # modules() may have triggered _ensure_bwd before this add)
        self.bwd_cell = None
        self._rec_pair = None
        return self

    def children(self):
        if self.batch_norm_params is not None and self.fwd_cell is not None:
            # bn mode: the runners OWN params (bias_pre, per-direction BN
            # gamma/beta) — they must be reachable from modules() or
            # get_weights/set_weights would silently skip those slots
            self._ensure_bwd()
            fwd, bwd = self._runners()
            return [fwd, bwd] + ([self.merge] if self.merge else [])
        return [c for c in (self.fwd_cell, self.bwd_cell, self.merge) if c]

    def _serde_children(self):
        # fixed-position slots (None placeholders) so restore is unambiguous
        return [self.fwd_cell, self.bwd_cell, self.merge]

    def _serde_restore_children(self, children):
        self.fwd_cell, self.bwd_cell, self.merge = children

    def _ensure_bwd(self):
        if self.bwd_cell is None:
            import copy
            self.bwd_cell = copy.deepcopy(self.fwd_cell)
            self.bwd_cell.name = f"{self.fwd_cell.name}_bwd"
            # children of deep-copied cells need distinct names too
            for m in self.bwd_cell.modules()[1:]:
                m.name = f"{m.name}_bwd"

    def init(self, rng):
        self._ensure_bwd()
        k1, k2, k3 = jax.random.split(rng, 3)
        fwd, bwd = self._runners()
        p = {}
        p.update(fwd.init(k1))
        p.update(bwd.init(k2))
        if self.merge is not None:
            p.update(self.merge.init(k3))
        return p

    def initial_state(self):
        if self.fwd_cell is None:
            return {}
        self._ensure_bwd()
        fwd, bwd = self._runners()
        st = dict(fwd.initial_state())
        st.update(bwd.initial_state())
        if self.merge is not None:
            st.update(self.merge.initial_state())
        return st

    def _runners(self):
        """Cached Recurrent wrappers: rebuilding them per forward would
        allocate fresh uids, so a stochastic cell's dropout base key
        (ctx.rng folds in the uid) would change every call — breaking
        same-key determinism (and growing the uid counter)."""
        pair = getattr(self, "_rec_pair", None)
        if pair is None or pair[0].cell is not self.fwd_cell \
                or pair[1].cell is not self.bwd_cell:
            bp = self.batch_norm_params
            pair = (Recurrent(self.fwd_cell, batch_norm_params=bp,
                              name=f"{self.name}_f"),
                    Recurrent(self.bwd_cell, batch_norm_params=bp,
                              name=f"{self.name}_b"))
            self._rec_pair = pair
        return pair

    def apply(self, params, x, ctx):
        self._ensure_bwd()
        fwd, bwd = self._runners()
        if self.is_split_input:
            if x.shape[-1] % 2:
                raise ValueError(
                    f"{self.name}: is_split_input needs an even feature "
                    f"dim, got {x.shape[-1]} "
                    "(≙ BifurcateSplitTable divisibility check)")
            half = x.shape[-1] // 2
            xf, xb = x[..., :half], x[..., half:]
        else:
            xf = xb = x
        yf = fwd.apply(params, xf, ctx)
        yb = jnp.flip(bwd.apply(params, jnp.flip(xb, axis=1), ctx), axis=1)
        if self.merge is None:
            return yf + yb
        return self.merge.apply(params, Table(yf, yb), ctx)


class RecurrentDecoder(Module):
    """Decoder: feeds its own output back as the next input for seq_length
    steps (nn/RecurrentDecoder.scala). Input is the first-step input (B, ...)."""

    def __init__(self, seq_length, cell=None, name=None):
        super().__init__(name=name)
        self.seq_length = seq_length
        self.cell = cell

    def add(self, cell):
        self.cell = cell
        return self

    def children(self):
        return [self.cell] if self.cell is not None else []

    def _serde_restore_children(self, children):
        if children and children[0] is not None:
            self.cell = children[0]

    def init(self, rng):
        return self.cell.init(rng)

    # stateful-decoding shell API shared with Recurrent (the reference
    # RecurrentDecoder extends Recurrent, RecurrentDecoder.scala:41)
    set_hidden_state = Recurrent.set_hidden_state
    clear_hidden_state = Recurrent.clear_hidden_state
    get_hidden_state = Recurrent.get_hidden_state
    _record_hidden = Recurrent._record_hidden

    def apply(self, params, x, ctx):
        init = getattr(self, "_user_hidden", None)
        if init is not None:
            from jax.core import Tracer
            if isinstance(x, Tracer):
                raise ValueError(
                    f"{self.name}: set_hidden_state is a shell-only API — "
                    "thread the initial hidden functionally under jit")
        hidden0 = init if init is not None \
            else self.cell.zero_hidden(x.shape[0], x.dtype)

        if ctx.training and ctx.rng_key is not None \
                and Recurrent._cell_is_stochastic(self.cell):
            def body(carry, _):
                inp, h, key = carry
                key, sub = jax.random.split(key)
                ctx.step_rng = sub
                out, h2 = self.cell.step(params, inp, h, ctx)
                return (out, h2, key), out

            carry, outs = lax.scan(body, (x, hidden0, ctx.rng(self)), None,
                                   length=self.seq_length)
            ctx.step_rng = None
            self._record_hidden(carry[1])
            return jnp.swapaxes(outs, 0, 1)

        def body(carry, _):
            inp, h = carry
            out, h2 = self.cell.step(params, inp, h, ctx)
            return (out, h2), out

        carry, outs = lax.scan(body, (x, hidden0), None,
                               length=self.seq_length)
        self._record_hidden(carry[1])
        return jnp.swapaxes(outs, 0, 1)


class TimeDistributed(Module):
    """Apply a module independently at each timestep of (B, T, ...)
    (nn/TimeDistributed.scala). Implemented by folding time into batch —
    one big MXU call instead of T small ones.

    ``mask_zero=True`` (≙ TimeDistributed.scala:114-130): output rows
    whose input (batch, time) row is all-zero are zeroed — the padding
    half of the reference's maskZero pipeline
    (LookupTable(maskZero) -> TimeDistributed(maskZero) ->
    Recurrent(maskZero))."""

    def __init__(self, layer, mask_zero=False, name=None):
        super().__init__(name=name)
        self.layer = layer
        self.mask_zero = bool(mask_zero)

    def children(self):
        return [self.layer]

    def init(self, rng):
        return self.layer.init(rng)

    def initial_state(self):
        return self.layer.initial_state()

    def apply(self, params, x, ctx):
        b, t = x.shape[0], x.shape[1]
        flat = x.reshape((b * t,) + x.shape[2:])
        y = self.layer.apply(params, flat, ctx)
        y = y.reshape((b, t) + y.shape[1:])
        if self.mask_zero:
            keep = jnp.any(x != 0, axis=tuple(range(2, x.ndim)))  # (B, T)
            y = jnp.where(keep.reshape((b, t) + (1,) * (y.ndim - 2)), y, 0)
        return y


class ConvLSTMPeephole3D(Cell):
    """Volumetric convolutional LSTM with peepholes over NCDHW maps
    (nn/ConvLSTMPeephole3D.scala); the 3D sibling of ConvLSTMPeephole,
    built on VolumetricConvolution (one fused 4x-gate conv per stream)."""

    def __init__(self, input_size, output_size, kernel_i, kernel_c,
                 stride=1, padding=-1, with_peephole=True, name=None):
        super().__init__(name=name)
        from .conv import VolumetricConvolution
        self.input_size = input_size
        self.output_size = output_size
        self.with_peephole = with_peephole
        self.conv_i = VolumetricConvolution(
            input_size, 4 * output_size, kernel_i, kernel_i, kernel_i,
            stride, stride, stride, padding, padding, padding,
            name=f"{self.name}_ci")
        self.conv_h = VolumetricConvolution(
            output_size, 4 * output_size, kernel_c, kernel_c, kernel_c,
            1, 1, 1, -1, -1, -1, with_bias=False, name=f"{self.name}_ch")

    def children(self):
        return [self.conv_i, self.conv_h]

    def init(self, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        p = {}
        p.update(self.conv_i.init(k1))
        p.update(self.conv_h.init(k2))
        if self.with_peephole:
            p[self.name] = {"peephole": 0.1 * jax.random.normal(
                k3, (3, self.output_size), jnp.float32)}
        return p

    def zero_hidden(self, batch_size, dtype=jnp.float32, spatial=None):
        if spatial is None:
            raise ValueError("ConvLSTMPeephole3D needs spatial dims")
        out_spatial = tuple(
            _conv_out_size(s, k, st, p) for s, k, st, p in zip(
                spatial, self.conv_i.kernel, self.conv_i.stride,
                self.conv_i.pad))
        shape = (batch_size, self.output_size) + out_spatial
        return Table(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))

    def step(self, params, x, hidden, ctx):
        h, c = as_list(hidden)
        z = (self.conv_i.apply(params, x, ctx)
             + self.conv_h.apply(params, h, ctx))
        i, f, g, o = jnp.split(z, 4, axis=1)
        if self.with_peephole:
            ph = self.own(params)["peephole"].astype(x.dtype)
            i = i + ph[0][None, :, None, None, None] * c
            f = f + ph[1][None, :, None, None, None] * c
        i, f = jax.nn.sigmoid(i), jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        c2 = f * c + i * g
        if self.with_peephole:
            o = o + ph[2][None, :, None, None, None] * c2
        o = jax.nn.sigmoid(o)
        h2 = o * jnp.tanh(c2)
        return h2, Table(h2, c2)
