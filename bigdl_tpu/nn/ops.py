"""TF-op shim modules (≙ nn/ops/*.scala + nn/tf/*.scala).

The reference implements each TensorFlow op as an `Operation` (a forward-
only Module) so imported TF graphs can execute on the BigDL runtime.  Here
every op is a stateless Module whose `apply` is one or two jnp/lax calls —
under jit the whole imported graph fuses into a single XLA program, so
these shims add zero dispatch overhead on TPU.

Multi-input ops take a Table/list input (like the reference's Table
activities).  Comparison/logical ops return bool arrays; Cast handles
dtype conversion.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .module import Module
from ..utils.table import as_list


class Operation(Module):
    """Forward-only op (≙ nn/ops/Operation.scala): backward is an error in
    the reference; under JAX most of these are differentiable anyway."""


def _pair(x):
    xs = as_list(x)
    return xs[0], xs[1]


# --------------------------------------------------------------------- #
# math                                                                  #
# --------------------------------------------------------------------- #
class Add(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a + b


class Subtract(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a - b


class Multiply(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a * b


class RealDiv(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a / b


class FloorDiv(Operation):
    """≙ nn/ops/FloorDiv.scala."""

    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return jnp.floor_divide(a, b)


class TruncateDiv(Operation):
    """≙ nn/ops/TruncateDiv.scala (C-style division, rounds toward 0)."""

    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return jnp.trunc(a / b).astype(a.dtype)


class Mod(Operation):
    """≙ nn/ops/Mod.scala (truncated, sign follows dividend)."""

    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a - jnp.trunc(a / b) * b


class FloorMod(Operation):
    """≙ nn/ops/FloorMod.scala (sign follows divisor)."""

    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return jnp.mod(a, b)


class Maximum(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return jnp.maximum(a, b)


class Minimum(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return jnp.minimum(a, b)


class Pow(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return jnp.power(a, b)


class SquaredDifference(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return (a - b) ** 2


class Inv(Operation):
    def apply(self, params, x, ctx):
        return 1.0 / x


class Sign(Operation):
    def apply(self, params, x, ctx):
        return jnp.sign(x)


class Rint(Operation):
    """Round to nearest even (≙ nn/ops/Rint.scala)."""

    def apply(self, params, x, ctx):
        return jnp.rint(x)


class Round(Operation):
    """Round half away from zero (≙ nn/ops/Round.scala)."""

    def apply(self, params, x, ctx):
        return jnp.trunc(x + jnp.sign(x) * 0.5)


class Ceil(Operation):
    def apply(self, params, x, ctx):
        return jnp.ceil(x)


class Floor(Operation):
    def apply(self, params, x, ctx):
        return jnp.floor(x)


class Exp(Operation):
    def apply(self, params, x, ctx):
        return jnp.exp(x)


class Expm1(Operation):
    def apply(self, params, x, ctx):
        return jnp.expm1(x)


class Erf(Operation):
    def apply(self, params, x, ctx):
        return jax.scipy.special.erf(x)


class Erfc(Operation):
    def apply(self, params, x, ctx):
        return jax.scipy.special.erfc(x)


class Lgamma(Operation):
    def apply(self, params, x, ctx):
        return jax.scipy.special.gammaln(x)


class Digamma(Operation):
    def apply(self, params, x, ctx):
        return jax.scipy.special.digamma(x)


class IsFinite(Operation):
    def apply(self, params, x, ctx):
        return jnp.isfinite(x)


class IsInf(Operation):
    def apply(self, params, x, ctx):
        return jnp.isinf(x)


class IsNan(Operation):
    def apply(self, params, x, ctx):
        return jnp.isnan(x)


class L2Loss(Operation):
    """sum(x^2)/2 (≙ nn/ops/L2Loss.scala)."""

    def apply(self, params, x, ctx):
        return jnp.sum(x.astype(jnp.float32) ** 2) / 2


class BatchMatMul(Operation):
    """≙ nn/ops/BatchMatMul.scala; adj flags transpose the last two dims."""

    def __init__(self, adj_x=False, adj_y=False, name=None):
        super().__init__(name=name)
        self.adj_x, self.adj_y = adj_x, adj_y

    def apply(self, params, x, ctx):
        a, b = _pair(x)
        if self.adj_x:
            a = jnp.swapaxes(a, -1, -2)
        if self.adj_y:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)


# --------------------------------------------------------------------- #
# reductions                                                            #
# --------------------------------------------------------------------- #
class Sum(Operation):
    """≙ nn/ops/Sum.scala: input (tensor, reduction_indices)."""

    def __init__(self, keep_dims=False, name=None):
        super().__init__(name=name)
        self.keep_dims = keep_dims

    def apply(self, params, x, ctx):
        t, idx = _pair(x)
        axes = tuple(int(i) for i in jnp.atleast_1d(jnp.asarray(idx)))
        return jnp.sum(t, axis=axes, keepdims=self.keep_dims)


class Prod(Operation):
    def __init__(self, axis=0, keep_dims=False, name=None):
        super().__init__(name=name)
        self.axis, self.keep_dims = axis, keep_dims

    def apply(self, params, x, ctx):
        return jnp.prod(x, axis=self.axis, keepdims=self.keep_dims)


class Max(Operation):
    """≙ nn/ops/Max.scala: (tensor, axis) pair input."""

    def __init__(self, keep_dims=False, name=None):
        super().__init__(name=name)
        self.keep_dims = keep_dims

    def apply(self, params, x, ctx):
        t, axis = _pair(x)
        return jnp.max(t, axis=int(axis), keepdims=self.keep_dims)


class All(Operation):
    def __init__(self, keep_dims=False, name=None):
        super().__init__(name=name)
        self.keep_dims = keep_dims

    def apply(self, params, x, ctx):
        t, idx = _pair(x)
        axes = tuple(int(i) for i in jnp.atleast_1d(jnp.asarray(idx)))
        return jnp.all(t.astype(bool), axis=axes, keepdims=self.keep_dims)


class Any(Operation):
    def __init__(self, keep_dims=False, name=None):
        super().__init__(name=name)
        self.keep_dims = keep_dims

    def apply(self, params, x, ctx):
        t, idx = _pair(x)
        axes = tuple(int(i) for i in jnp.atleast_1d(jnp.asarray(idx)))
        return jnp.any(t.astype(bool), axis=axes, keepdims=self.keep_dims)


class ArgMax(Operation):
    """≙ nn/ops/ArgMax.scala: (tensor, dimension) input, 0-based output."""

    def apply(self, params, x, ctx):
        t, axis = _pair(x)
        return jnp.argmax(t, axis=int(axis))


class SegmentSum(Operation):
    """≙ nn/ops/SegmentSum.scala: (data, segment_ids) with sorted ids."""

    def __init__(self, num_segments=None, name=None):
        super().__init__(name=name)
        self.num_segments = num_segments

    def apply(self, params, x, ctx):
        data, ids = _pair(x)
        n = self.num_segments
        if n is None:
            raise ValueError(
                f"{self.name}: num_segments must be static under jit")
        return jax.ops.segment_sum(data, ids.astype(jnp.int32),
                                   num_segments=n)


# --------------------------------------------------------------------- #
# comparisons / logical                                                 #
# --------------------------------------------------------------------- #
class Equal(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a == b


class NotEqual(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a != b


class Greater(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a > b


class GreaterEqual(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a >= b


class Less(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a < b


class LessEqual(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a <= b


class ApproximateEqual(Operation):
    def __init__(self, tolerance=1e-5, name=None):
        super().__init__(name=name)
        self.tolerance = tolerance

    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return jnp.abs(a - b) < self.tolerance


class LogicalAnd(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return jnp.logical_and(a, b)


class LogicalOr(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return jnp.logical_or(a, b)


class LogicalNot(Operation):
    def apply(self, params, x, ctx):
        return jnp.logical_not(x)


# --------------------------------------------------------------------- #
# shape / indexing                                                      #
# --------------------------------------------------------------------- #
class Cast(Operation):
    """≙ nn/ops/Cast.scala."""

    def __init__(self, dtype=jnp.float32, name=None):
        super().__init__(name=name)
        self.dtype = jnp.dtype(dtype)

    def apply(self, params, x, ctx):
        return x.astype(self.dtype)


class Shape(Operation):
    """≙ nn/tf/Shape.scala (static under jit)."""

    def apply(self, params, x, ctx):
        return jnp.asarray(x.shape, jnp.int32)


class Rank(Operation):
    def apply(self, params, x, ctx):
        return jnp.asarray(x.ndim, jnp.int32)


class Gather(Operation):
    """≙ nn/ops/Gather.scala: (params_tensor, indices) along `axis`."""

    def __init__(self, axis=0, name=None):
        super().__init__(name=name)
        self.axis = axis

    def apply(self, params, x, ctx):
        t, idx = _pair(x)
        return jnp.take(t, idx.astype(jnp.int32), axis=self.axis)


class OneHot(Operation):
    """≙ nn/ops/OneHot.scala."""

    def __init__(self, depth, on_value=1.0, off_value=0.0, axis=-1,
                 name=None):
        super().__init__(name=name)
        self.depth = depth
        self.on_value, self.off_value = on_value, off_value
        self.axis = axis

    def apply(self, params, x, ctx):
        oh = jax.nn.one_hot(x.astype(jnp.int32), self.depth, axis=self.axis)
        return oh * (self.on_value - self.off_value) + self.off_value


class Select(Operation):
    """≙ nn/ops/Select.scala: (condition, then, else)."""

    def apply(self, params, x, ctx):
        c, t, e = as_list(x)
        return jnp.where(c.astype(bool), t, e)


class Slice(Operation):
    """≙ nn/ops/Slice.scala: static begin/size."""

    def __init__(self, begin, size, name=None):
        super().__init__(name=name)
        self.begin, self.size = tuple(begin), tuple(size)

    def apply(self, params, x, ctx):
        size = tuple(x.shape[i] - b if s == -1 else s
                     for i, (b, s) in enumerate(zip(self.begin, self.size)))
        return lax.slice(x, self.begin,
                         tuple(b + s for b, s in zip(self.begin, size)))


class StrideSlice(Operation):
    """≙ nn/tf/StrideSlice.scala: list of (dim, start, stop, step)."""

    def __init__(self, specs, name=None):
        super().__init__(name=name)
        self.specs = specs

    def apply(self, params, x, ctx):
        idx = [slice(None)] * x.ndim
        for dim, start, stop, step in self.specs:
            idx[dim] = slice(start, stop, step)
        return x[tuple(idx)]


class Tile(Operation):
    """≙ nn/ops/Tile.scala: (tensor, multiples)."""

    def apply(self, params, x, ctx):
        t, mult = _pair(x)
        reps = tuple(int(m) for m in jnp.atleast_1d(jnp.asarray(mult)))
        return jnp.tile(t, reps)


class Pad(Operation):
    """≙ nn/ops/Pad.scala: (tensor, paddings [n,2])."""

    def __init__(self, mode="CONSTANT", constant_value=0.0, name=None):
        super().__init__(name=name)
        self.mode = mode.lower()
        self.constant_value = constant_value

    def apply(self, params, x, ctx):
        t, pads = _pair(x)
        import numpy as np
        pad_width = [(int(a), int(b)) for a, b in np.asarray(pads)]
        if self.mode == "constant":
            return jnp.pad(t, pad_width,
                           constant_values=self.constant_value)
        return jnp.pad(t, pad_width, mode=self.mode)


class RangeOps(Operation):
    """≙ nn/ops/RangeOps.scala: static (start, limit, delta)."""

    def __init__(self, start, limit, delta=1, name=None):
        super().__init__(name=name)
        self.start, self.limit, self.delta = start, limit, delta

    def apply(self, params, x, ctx):
        return jnp.arange(self.start, self.limit, self.delta)


class ExpandDims(Operation):
    def __init__(self, axis=0, name=None):
        super().__init__(name=name)
        self.axis = axis

    def apply(self, params, x, ctx):
        return jnp.expand_dims(x, self.axis)


class TopK(Operation):
    """≙ nn/ops/TopK.scala: returns (values, indices) table."""

    def __init__(self, k, sorted=True, name=None):
        super().__init__(name=name)
        self.k = k

    def apply(self, params, x, ctx):
        values, indices = lax.top_k(x, self.k)
        return [values, indices]


class InTopK(Operation):
    """≙ nn/ops/InTopK.scala: (predictions [N,C], targets [N])."""

    def __init__(self, k, name=None):
        super().__init__(name=name)
        self.k = k

    def apply(self, params, x, ctx):
        pred, tgt = _pair(x)
        _, top = lax.top_k(pred, self.k)
        return jnp.any(top == tgt.astype(top.dtype)[:, None], axis=-1)


# --------------------------------------------------------------------- #
# nn-flavored                                                           #
# --------------------------------------------------------------------- #
class BiasAdd(Operation):
    """≙ nn/tf/BiasAdd.scala: (value, bias) broadcast over last dim."""

    def apply(self, params, x, ctx):
        v, b = _pair(x)
        return v + b


class CrossEntropy(Operation):
    """Softmax cross entropy per row: (logits, one-hot labels)
    (≙ nn/ops/CrossEntropy.scala)."""

    def apply(self, params, x, ctx):
        logits, labels = _pair(x)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.sum(labels * logp, axis=-1)


class ResizeBilinear(Operation):
    """≙ nn/ops/ResizeBilinear.scala (NHWC)."""

    def __init__(self, out_height, out_width, align_corners=False,
                 name=None):
        super().__init__(name=name)
        self.out = (out_height, out_width)
        self.align_corners = align_corners

    def apply(self, params, x, ctx):
        n, h, w, c = x.shape
        method = "bilinear"
        return jax.image.resize(x, (n,) + self.out + (c,), method)


class RandomUniform(Operation):
    """≙ nn/ops/RandomUniform.scala."""

    def __init__(self, minval=0.0, maxval=1.0, name=None):
        super().__init__(name=name)
        self.minval, self.maxval = minval, maxval

    def apply(self, params, x, ctx):
        shape = tuple(int(s) for s in jnp.atleast_1d(jnp.asarray(x)))
        return jax.random.uniform(ctx.rng(self), shape,
                                  minval=self.minval, maxval=self.maxval)


class TruncatedNormal(Operation):
    """≙ nn/ops/TruncatedNormal.scala."""

    def __init__(self, mean=0.0, stddev=1.0, name=None):
        super().__init__(name=name)
        self.mean, self.stddev = mean, stddev

    def apply(self, params, x, ctx):
        shape = tuple(int(s) for s in jnp.atleast_1d(jnp.asarray(x)))
        z = jax.random.truncated_normal(ctx.rng(self), -2.0, 2.0, shape)
        return z * self.stddev + self.mean


class Assert(Operation):
    """≙ nn/tf/Assert.scala: passthrough (XLA has no host asserts; checks
    belong outside jit)."""

    def apply(self, params, x, ctx):
        xs = as_list(x)
        return xs[-1] if len(xs) > 1 else xs[0]


class NoOp(Operation):
    """≙ nn/tf/NoOp.scala."""

    def apply(self, params, x, ctx):
        return x


# --------------------------------------------------------------------- #
# feature-column ops                                                    #
# --------------------------------------------------------------------- #
class BucketizedCol(Operation):
    """Bucketize by boundaries (≙ nn/ops/BucketizedCol.scala)."""

    def __init__(self, boundaries, name=None):
        super().__init__(name=name)
        self.boundaries = jnp.asarray(boundaries, jnp.float32)

    def apply(self, params, x, ctx):
        return jnp.searchsorted(self.boundaries, x, side="right") \
            .astype(jnp.int32)


class Kv2Tensor(Operation):
    """'k1:v1,k2:v2' strings -> dense rows (host-side op; ≙
    nn/ops/Kv2Tensor.scala)."""

    def __init__(self, kv_delimiter=",", item_delimiter=":", dim=0,
                 name=None):
        super().__init__(name=name)
        self.kv_delimiter = kv_delimiter
        self.item_delimiter = item_delimiter
        self.dim = dim

    def apply(self, params, x, ctx):
        import numpy as np
        rows = []
        for s in x:
            row = np.zeros(self.dim, np.float32)
            for kv in str(s).split(self.kv_delimiter):
                k, v = kv.split(self.item_delimiter)
                row[int(k)] = float(v)
            rows.append(row)
        return jnp.asarray(np.stack(rows))


class MkString(Operation):
    """Join a row of values to a string (host-side; ≙ nn/ops/MkString.scala)."""

    def __init__(self, str_delimiter=",", name=None):
        super().__init__(name=name)
        self.delim = str_delimiter

    def apply(self, params, x, ctx):
        import numpy as np
        arr = np.asarray(x)
        return [self.delim.join(str(v) for v in row)
                for row in arr.reshape(arr.shape[0], -1)]
