"""TF-op shim modules (≙ nn/ops/*.scala + nn/tf/*.scala).

The reference implements each TensorFlow op as an `Operation` (a forward-
only Module) so imported TF graphs can execute on the BigDL runtime.  Here
every op is a stateless Module whose `apply` is one or two jnp/lax calls —
under jit the whole imported graph fuses into a single XLA program, so
these shims add zero dispatch overhead on TPU.

Multi-input ops take a Table/list input (like the reference's Table
activities).  Comparison/logical ops return bool arrays; Cast handles
dtype conversion.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .module import Module
from ..utils.table import as_list


class Operation(Module):
    """Forward-only op (≙ nn/ops/Operation.scala): backward is an error in
    the reference; under JAX most of these are differentiable anyway."""


def _pair(x):
    xs = as_list(x)
    return xs[0], xs[1]


# --------------------------------------------------------------------- #
# math                                                                  #
# --------------------------------------------------------------------- #
class Add(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a + b


class Subtract(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a - b


class Multiply(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a * b


class RealDiv(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a / b


class FloorDiv(Operation):
    """≙ nn/ops/FloorDiv.scala."""

    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return jnp.floor_divide(a, b)


class TruncateDiv(Operation):
    """≙ nn/ops/TruncateDiv.scala (C-style division, rounds toward 0)."""

    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return jnp.trunc(a / b).astype(a.dtype)


class Mod(Operation):
    """≙ nn/ops/Mod.scala (truncated, sign follows dividend)."""

    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a - jnp.trunc(a / b) * b


class FloorMod(Operation):
    """≙ nn/ops/FloorMod.scala (sign follows divisor)."""

    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return jnp.mod(a, b)


class Maximum(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return jnp.maximum(a, b)


class Minimum(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return jnp.minimum(a, b)


class Pow(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return jnp.power(a, b)


class SquaredDifference(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return (a - b) ** 2


class Inv(Operation):
    def apply(self, params, x, ctx):
        return 1.0 / x


class Sign(Operation):
    def apply(self, params, x, ctx):
        return jnp.sign(x)


class Rint(Operation):
    """Round to nearest even (≙ nn/ops/Rint.scala)."""

    def apply(self, params, x, ctx):
        return jnp.rint(x)


class Round(Operation):
    """Round half away from zero (≙ nn/ops/Round.scala)."""

    def apply(self, params, x, ctx):
        return jnp.trunc(x + jnp.sign(x) * 0.5)


class Ceil(Operation):
    def apply(self, params, x, ctx):
        return jnp.ceil(x)


class Floor(Operation):
    def apply(self, params, x, ctx):
        return jnp.floor(x)


class Exp(Operation):
    def apply(self, params, x, ctx):
        return jnp.exp(x)


class Expm1(Operation):
    def apply(self, params, x, ctx):
        return jnp.expm1(x)


class Erf(Operation):
    def apply(self, params, x, ctx):
        return jax.scipy.special.erf(x)


class Erfc(Operation):
    def apply(self, params, x, ctx):
        return jax.scipy.special.erfc(x)


class Lgamma(Operation):
    def apply(self, params, x, ctx):
        return jax.scipy.special.gammaln(x)


class Digamma(Operation):
    def apply(self, params, x, ctx):
        return jax.scipy.special.digamma(x)


class IsFinite(Operation):
    def apply(self, params, x, ctx):
        return jnp.isfinite(x)


class IsInf(Operation):
    def apply(self, params, x, ctx):
        return jnp.isinf(x)


class IsNan(Operation):
    def apply(self, params, x, ctx):
        return jnp.isnan(x)


class L2Loss(Operation):
    """sum(x^2)/2 (≙ nn/ops/L2Loss.scala)."""

    def apply(self, params, x, ctx):
        return jnp.sum(x.astype(jnp.float32) ** 2) / 2


class BatchMatMul(Operation):
    """≙ nn/ops/BatchMatMul.scala; adj flags transpose the last two dims."""

    def __init__(self, adj_x=False, adj_y=False, name=None):
        super().__init__(name=name)
        self.adj_x, self.adj_y = adj_x, adj_y

    def apply(self, params, x, ctx):
        a, b = _pair(x)
        if self.adj_x:
            a = jnp.swapaxes(a, -1, -2)
        if self.adj_y:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)


# --------------------------------------------------------------------- #
# reductions                                                            #
# --------------------------------------------------------------------- #
class Sum(Operation):
    """≙ nn/ops/Sum.scala: input (tensor, reduction_indices)."""

    def __init__(self, keep_dims=False, name=None):
        super().__init__(name=name)
        self.keep_dims = keep_dims

    def apply(self, params, x, ctx):
        t, idx = _pair(x)
        axes = tuple(int(i) for i in jnp.atleast_1d(jnp.asarray(idx)))
        return jnp.sum(t, axis=axes, keepdims=self.keep_dims)


class Prod(Operation):
    def __init__(self, axis=0, keep_dims=False, name=None):
        super().__init__(name=name)
        self.axis, self.keep_dims = axis, keep_dims

    def apply(self, params, x, ctx):
        return jnp.prod(x, axis=self.axis, keepdims=self.keep_dims)


class Max(Operation):
    """≙ nn/ops/Max.scala: (tensor, axis) pair input."""

    def __init__(self, keep_dims=False, name=None):
        super().__init__(name=name)
        self.keep_dims = keep_dims

    def apply(self, params, x, ctx):
        t, axis = _pair(x)
        return jnp.max(t, axis=int(axis), keepdims=self.keep_dims)


class All(Operation):
    def __init__(self, keep_dims=False, name=None):
        super().__init__(name=name)
        self.keep_dims = keep_dims

    def apply(self, params, x, ctx):
        t, idx = _pair(x)
        axes = tuple(int(i) for i in jnp.atleast_1d(jnp.asarray(idx)))
        return jnp.all(t.astype(bool), axis=axes, keepdims=self.keep_dims)


class Any(Operation):
    def __init__(self, keep_dims=False, name=None):
        super().__init__(name=name)
        self.keep_dims = keep_dims

    def apply(self, params, x, ctx):
        t, idx = _pair(x)
        axes = tuple(int(i) for i in jnp.atleast_1d(jnp.asarray(idx)))
        return jnp.any(t.astype(bool), axis=axes, keepdims=self.keep_dims)


class ArgMax(Operation):
    """≙ nn/ops/ArgMax.scala: (tensor, dimension) input, 0-based output."""

    def apply(self, params, x, ctx):
        t, axis = _pair(x)
        return jnp.argmax(t, axis=int(axis))


class SegmentSum(Operation):
    """≙ nn/ops/SegmentSum.scala: (data, segment_ids) with sorted ids."""

    def __init__(self, num_segments=None, name=None):
        super().__init__(name=name)
        self.num_segments = num_segments

    def apply(self, params, x, ctx):
        data, ids = _pair(x)
        n = self.num_segments
        if n is None:
            raise ValueError(
                f"{self.name}: num_segments must be static under jit")
        return jax.ops.segment_sum(data, ids.astype(jnp.int32),
                                   num_segments=n)


# --------------------------------------------------------------------- #
# comparisons / logical                                                 #
# --------------------------------------------------------------------- #
class Equal(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a == b


class NotEqual(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a != b


class Greater(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a > b


class GreaterEqual(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a >= b


class Less(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a < b


class LessEqual(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return a <= b


class ApproximateEqual(Operation):
    def __init__(self, tolerance=1e-5, name=None):
        super().__init__(name=name)
        self.tolerance = tolerance

    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return jnp.abs(a - b) < self.tolerance


class LogicalAnd(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return jnp.logical_and(a, b)


class LogicalOr(Operation):
    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return jnp.logical_or(a, b)


class LogicalNot(Operation):
    def apply(self, params, x, ctx):
        return jnp.logical_not(x)


# --------------------------------------------------------------------- #
# shape / indexing                                                      #
# --------------------------------------------------------------------- #
class Cast(Operation):
    """≙ nn/ops/Cast.scala."""

    def __init__(self, dtype=jnp.float32, name=None):
        super().__init__(name=name)
        self.dtype = jnp.dtype(dtype)

    def apply(self, params, x, ctx):
        return x.astype(self.dtype)


class Shape(Operation):
    """≙ nn/tf/Shape.scala (static under jit)."""

    def apply(self, params, x, ctx):
        return jnp.asarray(x.shape, jnp.int32)


class Rank(Operation):
    def apply(self, params, x, ctx):
        return jnp.asarray(x.ndim, jnp.int32)


class Gather(Operation):
    """≙ nn/ops/Gather.scala: (params_tensor, indices) along `axis`."""

    def __init__(self, axis=0, name=None):
        super().__init__(name=name)
        self.axis = axis

    def apply(self, params, x, ctx):
        t, idx = _pair(x)
        return jnp.take(t, idx.astype(jnp.int32), axis=self.axis)


class OneHot(Operation):
    """≙ nn/ops/OneHot.scala."""

    def __init__(self, depth, on_value=1.0, off_value=0.0, axis=-1,
                 name=None):
        super().__init__(name=name)
        self.depth = depth
        self.on_value, self.off_value = on_value, off_value
        self.axis = axis

    def apply(self, params, x, ctx):
        oh = jax.nn.one_hot(x.astype(jnp.int32), self.depth, axis=self.axis)
        return oh * (self.on_value - self.off_value) + self.off_value


class Select(Operation):
    """≙ nn/ops/Select.scala: (condition, then, else)."""

    def apply(self, params, x, ctx):
        c, t, e = as_list(x)
        return jnp.where(c.astype(bool), t, e)


class Slice(Operation):
    """≙ nn/ops/Slice.scala: static begin/size."""

    def __init__(self, begin, size, name=None):
        super().__init__(name=name)
        self.begin, self.size = tuple(begin), tuple(size)

    def apply(self, params, x, ctx):
        size = tuple(x.shape[i] - b if s == -1 else s
                     for i, (b, s) in enumerate(zip(self.begin, self.size)))
        return lax.slice(x, self.begin,
                         tuple(b + s for b, s in zip(self.begin, size)))


class StrideSlice(Operation):
    """≙ nn/tf/StrideSlice.scala: list of (dim, start, stop, step)."""

    def __init__(self, specs, name=None):
        super().__init__(name=name)
        self.specs = specs

    def apply(self, params, x, ctx):
        idx = [slice(None)] * x.ndim
        for dim, start, stop, step in self.specs:
            idx[dim] = slice(start, stop, step)
        return x[tuple(idx)]


class Tile(Operation):
    """≙ nn/ops/Tile.scala: (tensor, multiples)."""

    def apply(self, params, x, ctx):
        t, mult = _pair(x)
        reps = tuple(int(m) for m in jnp.atleast_1d(jnp.asarray(mult)))
        return jnp.tile(t, reps)


class Pad(Operation):
    """≙ nn/ops/Pad.scala: (tensor, paddings [n,2])."""

    def __init__(self, mode="CONSTANT", constant_value=0.0, name=None):
        super().__init__(name=name)
        self.mode = mode.lower()
        self.constant_value = constant_value

    def apply(self, params, x, ctx):
        t, pads = _pair(x)
        import numpy as np
        pad_width = [(int(a), int(b)) for a, b in np.asarray(pads)]
        if self.mode == "constant":
            return jnp.pad(t, pad_width,
                           constant_values=self.constant_value)
        return jnp.pad(t, pad_width, mode=self.mode)


class RangeOps(Operation):
    """≙ nn/ops/RangeOps.scala: static (start, limit, delta)."""

    def __init__(self, start, limit, delta=1, name=None):
        super().__init__(name=name)
        self.start, self.limit, self.delta = start, limit, delta

    def apply(self, params, x, ctx):
        return jnp.arange(self.start, self.limit, self.delta)


class ExpandDims(Operation):
    def __init__(self, axis=0, name=None):
        super().__init__(name=name)
        self.axis = axis

    def apply(self, params, x, ctx):
        return jnp.expand_dims(x, self.axis)


class TopK(Operation):
    """≙ nn/ops/TopK.scala: returns (values, indices) table."""

    def __init__(self, k, sorted=True, name=None):
        super().__init__(name=name)
        self.k = k

    def apply(self, params, x, ctx):
        values, indices = lax.top_k(x, self.k)
        return [values, indices]


class InTopK(Operation):
    """≙ nn/ops/InTopK.scala: (predictions [N,C], targets [N])."""

    def __init__(self, k, name=None):
        super().__init__(name=name)
        self.k = k

    def apply(self, params, x, ctx):
        pred, tgt = _pair(x)
        _, top = lax.top_k(pred, self.k)
        return jnp.any(top == tgt.astype(top.dtype)[:, None], axis=-1)


# --------------------------------------------------------------------- #
# nn-flavored                                                           #
# --------------------------------------------------------------------- #
class BiasAdd(Operation):
    """≙ nn/tf/BiasAdd.scala: (value, bias) broadcast over last dim."""

    def apply(self, params, x, ctx):
        v, b = _pair(x)
        return v + b


class CrossEntropy(Operation):
    """Softmax cross entropy per row: (logits, one-hot labels)
    (≙ nn/ops/CrossEntropy.scala)."""

    def apply(self, params, x, ctx):
        logits, labels = _pair(x)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.sum(labels * logp, axis=-1)


class ResizeBilinear(Operation):
    """≙ nn/ops/ResizeBilinear.scala (NHWC)."""

    def __init__(self, out_height, out_width, align_corners=False,
                 name=None):
        super().__init__(name=name)
        self.out = (out_height, out_width)
        self.align_corners = align_corners

    def apply(self, params, x, ctx):
        n, h, w, c = x.shape
        method = "bilinear"
        return jax.image.resize(x, (n,) + self.out + (c,), method)


class RandomUniform(Operation):
    """≙ nn/ops/RandomUniform.scala."""

    def __init__(self, minval=0.0, maxval=1.0, name=None):
        super().__init__(name=name)
        self.minval, self.maxval = minval, maxval

    def apply(self, params, x, ctx):
        shape = tuple(int(s) for s in jnp.atleast_1d(jnp.asarray(x)))
        return jax.random.uniform(ctx.rng(self), shape,
                                  minval=self.minval, maxval=self.maxval)


class TruncatedNormal(Operation):
    """≙ nn/ops/TruncatedNormal.scala."""

    def __init__(self, mean=0.0, stddev=1.0, name=None):
        super().__init__(name=name)
        self.mean, self.stddev = mean, stddev

    def apply(self, params, x, ctx):
        shape = tuple(int(s) for s in jnp.atleast_1d(jnp.asarray(x)))
        z = jax.random.truncated_normal(ctx.rng(self), -2.0, 2.0, shape)
        return z * self.stddev + self.mean


class Assert(Operation):
    """≙ nn/tf/Assert.scala: passthrough (XLA has no host asserts; checks
    belong outside jit)."""

    def apply(self, params, x, ctx):
        xs = as_list(x)
        return xs[-1] if len(xs) > 1 else xs[0]


class NoOp(Operation):
    """≙ nn/tf/NoOp.scala."""

    def apply(self, params, x, ctx):
        return x


# --------------------------------------------------------------------- #
# feature-column ops                                                    #
# --------------------------------------------------------------------- #
class BucketizedCol(Operation):
    """Bucketize by boundaries (≙ nn/ops/BucketizedCol.scala)."""

    def __init__(self, boundaries, name=None):
        super().__init__(name=name)
        self.boundaries = jnp.asarray(boundaries, jnp.float32)

    def apply(self, params, x, ctx):
        return jnp.searchsorted(self.boundaries, x, side="right") \
            .astype(jnp.int32)


class Kv2Tensor(Operation):
    """'k1:v1,k2:v2' strings -> dense rows (host-side op; ≙
    nn/ops/Kv2Tensor.scala)."""

    def __init__(self, kv_delimiter=",", item_delimiter=":", dim=0,
                 name=None):
        super().__init__(name=name)
        self.kv_delimiter = kv_delimiter
        self.item_delimiter = item_delimiter
        self.dim = dim

    def apply(self, params, x, ctx):
        import numpy as np
        rows = []
        for s in x:
            row = np.zeros(self.dim, np.float32)
            for kv in str(s).split(self.kv_delimiter):
                k, v = kv.split(self.item_delimiter)
                row[int(k)] = float(v)
            rows.append(row)
        return jnp.asarray(np.stack(rows))


class MkString(Operation):
    """Join a row of values to a string (host-side; ≙ nn/ops/MkString.scala)."""

    def __init__(self, str_delimiter=",", name=None):
        super().__init__(name=name)
        self.delim = str_delimiter

    def apply(self, params, x, ctx):
        import numpy as np
        arr = np.asarray(x)
        return [self.delim.join(str(v) for v in row)
                for row in arr.reshape(arr.shape[0], -1)]


class CategoricalColHashBucket(Operation):
    """Feature strings -> hashed bucket id rows (≙
    nn/ops/CategoricalColHashBucket.scala).  Host-side (string input);
    multi-value cells split on `str_delimiter`.  Returns a
    tensor.SparseTensor when is_sparse else a dense padded id matrix."""

    def __init__(self, hash_bucket_size, str_delimiter=",", is_sparse=True,
                 name=None):
        super().__init__(name=name)
        self.hash_bucket_size = hash_bucket_size
        self.str_delimiter = str_delimiter
        self.is_sparse = is_sparse

    def _bucket(self, s):
        import zlib
        return zlib.crc32(str(s).encode()) % self.hash_bucket_size

    def apply(self, params, x, ctx):
        import numpy as np
        rows = [[self._bucket(v) for v in str(s).split(self.str_delimiter)]
                for s in x]
        width = max(len(r) for r in rows)
        if self.is_sparse:
            from ..tensor import SparseTensor
            idx, vals = [], []
            for i, r in enumerate(rows):
                for j, v in enumerate(r):
                    idx.append((i, j))
                    vals.append(v)
            return SparseTensor(np.asarray(idx, np.int32).T,
                                np.asarray(vals, np.int32),
                                (len(rows), width))
        out = np.zeros((len(rows), width), np.int32)
        for i, r in enumerate(rows):
            out[i, :len(r)] = r
        return jnp.asarray(out)


class CategoricalColVocaList(Operation):
    """Feature strings -> vocabulary ids (≙ nn/ops/CategoricalColVocaList
    .scala).  Out-of-vocabulary values map to `len(vocab) + hash % num_oov`
    when num_oov_buckets > 0, else to `default_value`."""

    def __init__(self, vocab_list, str_delimiter=",", is_sparse=True,
                 num_oov_buckets=0, default_value=-1, name=None):
        super().__init__(name=name)
        self.vocab = {v: i for i, v in enumerate(vocab_list)}
        self.str_delimiter = str_delimiter
        self.is_sparse = is_sparse
        self.num_oov_buckets = num_oov_buckets
        self.default_value = default_value

    def _lookup(self, s):
        import zlib
        if s in self.vocab:
            return self.vocab[s]
        if self.num_oov_buckets > 0:
            return len(self.vocab) + (zlib.crc32(s.encode())
                                      % self.num_oov_buckets)
        return self.default_value

    def apply(self, params, x, ctx):
        import numpy as np
        rows = [[self._lookup(v) for v in str(s).split(self.str_delimiter)]
                for s in x]
        width = max(len(r) for r in rows)
        if self.is_sparse:
            from ..tensor import SparseTensor
            idx, vals = [], []
            for i, r in enumerate(rows):
                for j, v in enumerate(r):
                    idx.append((i, j))
                    vals.append(v)
            return SparseTensor(np.asarray(idx, np.int32).T,
                                np.asarray(vals, np.int32),
                                (len(rows), width))
        out = np.full((len(rows), width), self.default_value, np.int32)
        for i, r in enumerate(rows):
            out[i, :len(r)] = r
        return jnp.asarray(out)


class CrossCol(Operation):
    """Cross of categorical string columns: hash(cartesian product) %
    hash_bucket_size (≙ nn/ops/CrossCol.scala).  Input: Table of
    equal-length string lists; output SparseTensor of bucket ids."""

    def __init__(self, hash_bucket_size, str_delimiter=",", name=None):
        super().__init__(name=name)
        self.hash_bucket_size = hash_bucket_size
        self.str_delimiter = str_delimiter

    def apply(self, params, x, ctx):
        import itertools
        import zlib
        import numpy as np
        cols = [list(c) for c in as_list(x)]
        n = len(cols[0])
        idx, vals = [], []
        width = 1
        for i in range(n):
            cells = [str(c[i]).split(self.str_delimiter) for c in cols]
            crossed = [zlib.crc32("_X_".join(combo).encode())
                       % self.hash_bucket_size
                       for combo in itertools.product(*cells)]
            width = max(width, len(crossed))
            for j, v in enumerate(crossed):
                idx.append((i, j))
                vals.append(v)
        from ..tensor import SparseTensor
        return SparseTensor(np.asarray(idx, np.int32).T,
                            np.asarray(vals, np.int32), (n, width))


class IndicatorCol(Operation):
    """Categorical id SparseTensor -> multi-hot dense indicator matrix
    (≙ nn/ops/IndicatorCol.scala)."""

    def __init__(self, feature_num, is_count=True, name=None):
        super().__init__(name=name)
        self.feature_num = feature_num
        self.is_count = is_count

    def apply(self, params, x, ctx):
        import numpy as np
        from ..tensor import SparseTensor
        if isinstance(x, SparseTensor):
            rows = np.asarray(x.indices[0])
            ids = np.asarray(x.values).astype(np.int64)
            n = x.shape[0]
        else:
            arr = np.asarray(x).astype(np.int64)
            rows = np.repeat(np.arange(arr.shape[0]), arr.shape[1])
            ids = arr.reshape(-1)
            n = arr.shape[0]
        out = np.zeros((n, self.feature_num), np.float32)
        for r, i in zip(rows, ids):
            if 0 <= i < self.feature_num:
                if self.is_count:
                    out[r, i] += 1.0
                else:
                    out[r, i] = 1.0
        return jnp.asarray(out)


class Substr(Operation):
    """Substring of a scalar string: Table(str, pos, len) -> str
    (≙ nn/ops/Substr.scala)."""

    def apply(self, params, x, ctx):
        data, pos, length = as_list(x)[:3]
        p, n = int(pos), int(length)
        return str(data)[p:p + n]


class Compare(Operation):
    """Abstract elementwise comparison base (≙ nn/ops/Compare.scala);
    concrete subclasses: Greater/GreaterEqual/Less/LessEqual/Equal/
    NotEqual above."""

    def compare(self, a, b):
        raise NotImplementedError

    def apply(self, params, x, ctx):
        a, b = _pair(x)
        return self.compare(a, b)


class DepthwiseConv2D(Operation):
    """Runtime-filter depthwise conv: Table(input, filter) -> output
    (≙ nn/ops/DepthwiseConv2D.scala).  filter is HWIO-style
    (kh, kw, in_channels, channel_multiplier); data_format NHWC or NCHW."""

    def __init__(self, stride_w=1, stride_h=1, pad_w=0, pad_h=0,
                 data_format="NHWC", name=None):
        super().__init__(name=name)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.data_format = data_format

    def apply(self, params, x, ctx):
        inp, filt = _pair(x)
        kh, kw, cin, mult = filt.shape
        # OIHW with feature_group_count=cin: (cin*mult, 1, kh, kw)
        w = jnp.transpose(filt, (2, 3, 0, 1)).reshape(cin * mult, 1, kh, kw)
        dn = ("NHWC", "OIHW", "NHWC") if self.data_format == "NHWC" \
            else ("NCHW", "OIHW", "NCHW")
        pads = [(self.pad[0], self.pad[0]), (self.pad[1], self.pad[1])] \
            if self.pad != (-1, -1) else "SAME"
        return jax.lax.conv_general_dilated(
            inp, w.astype(inp.dtype), window_strides=self.stride,
            padding=pads, feature_group_count=cin, dimension_numbers=dn)


class Dilation2D(Operation):
    """Grayscale morphological dilation (max-sum correlation):
    Table(input NHWC, filter (kh, kw, depth)) -> NHWC
    (≙ nn/ops/Dilation2D.scala)."""

    def __init__(self, strides=(1, 1, 1, 1), rates=(1, 1, 1, 1),
                 padding="VALID", name=None):
        super().__init__(name=name)
        self.strides = strides
        self.rates = rates
        self.padding = padding.upper()

    def apply(self, params, x, ctx):
        inp, filt = _pair(x)
        kh, kw, depth = filt.shape
        rh, rw = self.rates[1], self.rates[2]
        sh, sw = self.strides[1], self.strides[2]
        eff_kh, eff_kw = (kh - 1) * rh + 1, (kw - 1) * rw + 1
        b, h, w_, d = inp.shape
        if self.padding == "SAME":
            out_h = -(-h // sh)
            out_w = -(-w_ // sw)
            pad_h = max(0, (out_h - 1) * sh + eff_kh - h)
            pad_w = max(0, (out_w - 1) * sw + eff_kw - w_)
            pads = ((pad_h // 2, pad_h - pad_h // 2),
                    (pad_w // 2, pad_w - pad_w // 2))
        else:
            out_h = (h - eff_kh) // sh + 1
            out_w = (w_ - eff_kw) // sw + 1
            pads = ((0, 0), (0, 0))
        neg = jnp.asarray(-jnp.inf, inp.dtype)
        xp = jnp.pad(inp, ((0, 0), pads[0], pads[1], (0, 0)),
                     constant_values=neg)
        # max over kernel taps of (patch + filter tap) — small kernel loop
        # unrolled at trace time (static), each tap a strided slice
        out = None
        for i in range(kh):
            for j in range(kw):
                patch = jax.lax.slice(
                    xp, (0, i * rh, j * rw, 0),
                    (b, i * rh + (out_h - 1) * sh + 1,
                     j * rw + (out_w - 1) * sw + 1, d),
                    (1, sh, sw, 1))
                cand = patch + filt[i, j]
                out = cand if out is None else jnp.maximum(out, cand)
        return out


class ModuleToOperation(Operation):
    """Adapt any Module to the forward-only Operation interface
    (≙ nn/ops/ModuleToOperation.scala)."""

    def __init__(self, module, name=None):
        super().__init__(name=name)
        self.module = module

    def children(self):
        return [self.module]

    def _serde_restore_children(self, children):
        if children and children[0] is not None:
            self.module = children[0]

    def init(self, rng):
        return self.module.init(rng)

    def initial_state(self):
        return self.module.initial_state()

    def apply(self, params, x, ctx):
        return self.module.apply(params, x, ctx)


class TensorOp(Operation):
    """Chainable closure op over tensors (≙ nn/ops/TensorOp.scala):
    ``TensorOp.identity().abs().sqrt()`` composes transformations; apply
    runs them left-to-right."""

    def __init__(self, fns=None, name=None):
        super().__init__(name=name)
        self._fns = list(fns or [])

    @classmethod
    def identity(cls):
        return cls()

    def _chain(self, f):
        return TensorOp(self._fns + [f])

    def abs(self):
        return self._chain(jnp.abs)

    def sqrt(self):
        return self._chain(jnp.sqrt)

    def square(self):
        return self._chain(jnp.square)

    def exp(self):
        return self._chain(jnp.exp)

    def log(self):
        return self._chain(jnp.log)

    def negative(self):
        return self._chain(jnp.negative)

    def sigmoid(self):
        return self._chain(jax.nn.sigmoid)

    def tanh(self):
        return self._chain(jnp.tanh)

    def add(self, v):
        return self._chain(lambda x: x + v)

    def sub(self, v):
        return self._chain(lambda x: x - v)

    def mul(self, v):
        return self._chain(lambda x: x * v)

    def div(self, v):
        return self._chain(lambda x: x / v)

    def pow(self, v):
        return self._chain(lambda x: x ** v)

    def apply(self, params, x, ctx):
        for f in self._fns:
            x = f(x)
        return x


# --------------------------------------------------------------------- #
# nn/tf shims with standalone value (the reference's remaining nn/tf/*
# classes — TensorArray*, Conv*Backprop*, *Grad — are TF-importer
# plumbing for hand-written backward graphs; JAX AD subsumes them)      #
# --------------------------------------------------------------------- #
class Const(Operation):
    """Emit a constant regardless of input (≙ nn/tf/ArrayOps.scala Const)."""

    def __init__(self, value, name=None):
        super().__init__(name=name)
        self.value = jnp.asarray(value)

    def apply(self, params, x, ctx):
        return self.value


class Fill(Operation):
    """Table(shape, scalar) -> filled tensor (≙ ArrayOps.scala Fill)."""

    def apply(self, params, x, ctx):
        shape, value = _pair(x)
        import numpy as np
        dims = tuple(int(d) for d in np.asarray(shape).reshape(-1))
        return jnp.full(dims, value)


class InvertPermutation(Operation):
    """y[x[i]] = i (≙ ArrayOps.scala InvertPermutation)."""

    def apply(self, params, x, ctx):
        x = x.astype(jnp.int32)
        return jnp.zeros_like(x).at[x].set(jnp.arange(x.shape[0],
                                                      dtype=jnp.int32))
