"""Inference-graph fusion: fold BatchNorm into the preceding conv/linear.

For eval-mode inference BN is an affine function of its RUNNING stats:
``y = gamma * (x - mu) / sqrt(var + eps) + beta``.  When x is the output
of a convolution or linear layer, the whole BN folds exactly into that
layer's weights:

    s  = gamma / sqrt(var + eps)          (per output channel)
    w' = w * s                            (scale output-channel rows)
    b' = (b - mu) * s + beta

One fewer elementwise pass over the activations per BN — on TPU these
passes are HBM-bandwidth-bound, so folding directly raises inference
throughput (and removes the BN dequant/requant pair on the int8 path).
The reference keeps BN separate at inference (nn/BatchNormalization.scala
eval branch); folding is the TPU-native equivalent of its MKL-era fused
primitives.

Training is untouched: ``fold_batchnorm`` returns a NEW model for
serving; batch statistics still drive the training graph.
"""
from __future__ import annotations

import copy

import numpy as np

from .containers import Container, Sequential
from .conv import SpatialConvolution
from .graph import Graph
from .linear import Linear
from .normalization import BatchNormalization

__all__ = ["fold_batchnorm"]


def _bn_affine(bn, params, state):
    """(scale, shift) of the eval-mode BN as numpy vectors."""
    st = state.get(bn.name, {})
    mu = np.asarray(st.get("running_mean", np.zeros(bn.n_output)),
                    np.float32)
    var = np.asarray(st.get("running_var", np.ones(bn.n_output)),
                     np.float32)
    inv = 1.0 / np.sqrt(var + bn.eps)
    if bn.affine:
        own = params.get(bn.name, {})
        gamma = np.asarray(own.get("weight", np.ones(bn.n_output)),
                           np.float32)
        beta = np.asarray(own.get("bias", np.zeros(bn.n_output)),
                          np.float32)
    else:
        gamma = np.ones(bn.n_output, np.float32)
        beta = np.zeros(bn.n_output, np.float32)
    return gamma * inv, beta - mu * gamma * inv


def _foldable(mod, bn, params):
    """conv/linear directly feeding a BN with matching channel count."""
    if not isinstance(bn, BatchNormalization):    # covers Spatial subclass
        return False
    own = params.get(mod.name)
    if not own or "weight" not in own:
        return False
    if isinstance(mod, SpatialConvolution):
        return mod.n_output_plane == bn.n_output
    if isinstance(mod, Linear):
        return mod.output_size == bn.n_output
    return False


def _fold_pair(mod, bn, params, state):
    """Rewrite mod's params in place (in the params dict) with BN folded."""
    scale, shift = _bn_affine(bn, params, state)
    own = dict(params[mod.name])
    w = np.asarray(own["weight"], np.float32)
    # both layouts put the output channel on dim 0 (conv OIHW, linear
    # (out, in)) — scale rows
    own["weight"] = w * scale.reshape((-1,) + (1,) * (w.ndim - 1))
    b = np.asarray(own.get("bias", np.zeros(scale.shape[0])), np.float32)
    own["bias"] = b * scale + shift
    params[mod.name] = own
    mod.with_bias = True


def fold_batchnorm(model):
    """Return a NEW model (deep copy) with every Sequential's adjacent
    conv→BN / linear→BN pair folded and the BN layer removed.

    The input model must be initialized (params + running stats).  Pairs
    inside nested containers are folded recursively; BNs that do not
    directly follow a foldable layer are left as-is.
    """
    params = model.ensure_initialized()
    state = dict(getattr(model, "_state", None) or {})
    new_model = copy.deepcopy(model)
    new_params = copy.deepcopy(
        {k: dict(v) if isinstance(v, dict) else v for k, v in params.items()})
    new_state = dict(state)

    # Weight sharing guard: params are keyed by module NAME, so a module
    # reused at several sites (same instance, or any name collision)
    # shares one params slot — folding it once would corrupt every other
    # use site.  Count occurrences across the WHOLE model up front; both
    # the Graph and the Sequential paths refuse to fold any pair whose
    # conv/linear or BN appears more than once.
    occurrences = {}

    def count(m):
        if isinstance(m, Graph):
            for n in m._topo:
                if n.module is not None:
                    count(n.module)
            return
        occurrences[m.name] = occurrences.get(m.name, 0) + 1
        if isinstance(m, Container):
            for c in m.children():
                count(c)

    count(new_model)

    def fold_graph(g):
        """Splice conv->BN edges out of a DAG: fold when the BN is the
        conv's ONLY consumer (otherwise other consumers would see the
        folded activation)."""
        consumers = {}
        node_count = {}      # module identity -> number of graph nodes
        for n in g._topo:
            if n.module is not None:
                node_count[id(n.module)] = node_count.get(
                    id(n.module), 0) + 1
            for prev in n.prev_nodes:
                consumers.setdefault(id(prev), []).append(n)
        for b in list(g._topo):
            if b.module is None \
                    or not isinstance(b.module, BatchNormalization) \
                    or len(b.prev_nodes) != 1:
                continue
            a = b.prev_nodes[0]
            if a.module is None \
                    or not _foldable(a.module, b.module, new_params) \
                    or len(consumers.get(id(a), [])) != 1 \
                    or any(n is a for n in g.output_nodes):
                continue
            # weight sharing: the same module at MULTIPLE graph nodes
            # (siamese nets) — folding would corrupt the other use sites
            if node_count.get(id(a.module), 0) != 1 \
                    or node_count.get(id(b.module), 0) != 1 \
                    or occurrences.get(a.module.name, 0) > 1 \
                    or occurrences.get(b.module.name, 0) > 1:
                continue
            _fold_pair(a.module, b.module, new_params, new_state)
            new_params.pop(b.module.name, None)
            new_state.pop(b.module.name, None)
            for c in consumers.get(id(b), []):
                c.prev_nodes = [a if prev is b else prev
                                for prev in c.prev_nodes]
            g.output_nodes = [a if n is b else n for n in g.output_nodes]
            consumers[id(a)] = consumers.pop(id(b), [])
        g._topo = g._topsort()

    def walk(container):
        if isinstance(container, Graph):
            for child in container.children():
                walk(child)
            fold_graph(container)
            return
        if not isinstance(container, Container):
            return
        for child in container.children():
            walk(child)
        if not isinstance(container, Sequential):
            return
        kids = container.children()
        keep = []
        i = 0
        while i < len(kids):
            mod = kids[i]
            nxt = kids[i + 1] if i + 1 < len(kids) else None
            if nxt is not None and _foldable(mod, nxt, new_params) \
                    and occurrences.get(mod.name, 0) == 1 \
                    and occurrences.get(nxt.name, 0) == 1:
                _fold_pair(mod, nxt, new_params, new_state)
                new_params.pop(nxt.name, None)
                new_state.pop(nxt.name, None)
                keep.append(mod)
                i += 2
                continue
            keep.append(mod)
            i += 1
        container._children = keep

    walk(new_model)
    new_model.set_params(new_params, new_state)
    new_model.evaluate()
    return new_model
