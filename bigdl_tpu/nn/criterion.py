"""Loss functions (criterions).

Reference files: nn/ClassNLLCriterion.scala, CrossEntropyCriterion.scala,
MSECriterion.scala, AbsCriterion.scala, BCECriterion.scala,
MultiCriterion.scala, ParallelCriterion.scala, SmoothL1Criterion.scala,
MarginCriterion.scala, MarginRankingCriterion.scala, HingeEmbeddingCriterion.scala,
L1HingeEmbeddingCriterion.scala, CosineEmbeddingCriterion.scala,
CosineDistanceCriterion.scala, CosineProximityCriterion.scala,
DistKLDivCriterion.scala, KLDCriterion.scala, GaussianCriterion.scala,
MultiLabelMarginCriterion.scala, MultiLabelSoftMarginCriterion.scala,
MultiMarginCriterion.scala, SoftMarginCriterion.scala, ClassSimplexCriterion.scala,
DiceCoefficientCriterion.scala, MeanAbsolutePercentageCriterion.scala,
MeanSquaredLogarithmicCriterion.scala, KullbackLeiblerDivergenceCriterion.scala,
PoissonCriterion.scala, L1Cost.scala, DotProductCriterion.scala, PGCriterion.scala,
TimeDistributedCriterion.scala, TimeDistributedMaskCriterion.scala,
CategoricalCrossEntropy.scala, SoftmaxWithCriterion.scala,
CrossEntropy (ops), ClassNLL label convention: **targets are 1-based**
class indices (Torch heritage), preserved here for API parity.

Gradients come from JAX AD (Criterion.backward), so only the scalar loss is
defined per criterion.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .module import Criterion
from ..utils.table import as_list


def _reduce(per_elem, size_average, weight_sum=None):
    if size_average:
        if weight_sum is not None:
            return jnp.sum(per_elem) / jnp.maximum(weight_sum, 1e-12)
        return jnp.mean(per_elem)
    return jnp.sum(per_elem)


class ClassNLLCriterion(Criterion):
    """Negative log-likelihood over log-probabilities with 1-based integer
    targets (nn/ClassNLLCriterion.scala).  `padding_value` targets contribute
    zero loss; `logProbAsInput=False` takes probabilities instead."""

    def __init__(self, weights=None, size_average=True, log_prob_as_input=True,
                 padding_value=-1, zero_based_label=False, name=None):
        super().__init__(name=name)
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average
        self.log_prob_as_input = log_prob_as_input
        self.padding_value = padding_value
        self.zero_based_label = zero_based_label

    def loss(self, output, target):
        logp = output if self.log_prob_as_input else jnp.log(
            jnp.maximum(output, 1e-8))
        t = target.astype(jnp.int32).reshape(-1)
        idx = t if self.zero_based_label else t - 1
        valid = (t != self.padding_value)
        idx_c = jnp.clip(idx, 0, logp.shape[-1] - 1)
        logp2 = logp.reshape(-1, logp.shape[-1])
        picked = jnp.take_along_axis(logp2, idx_c[:, None], axis=-1)[:, 0]
        w = jnp.ones_like(picked) if self.weights is None \
            else jnp.take(self.weights, idx_c)
        w = w * valid.astype(picked.dtype)
        return _reduce(-w * picked, self.size_average, jnp.sum(w))


class CrossEntropyCriterion(Criterion):
    """LogSoftMax + ClassNLL fused (nn/CrossEntropyCriterion.scala)."""

    def __init__(self, weights=None, size_average=True, zero_based_label=False,
                 name=None):
        super().__init__(name=name)
        self.nll = ClassNLLCriterion(weights, size_average,
                                     zero_based_label=zero_based_label)

    def loss(self, output, target):
        return self.nll.loss(jax.nn.log_softmax(output, axis=-1), target)


class CategoricalCrossEntropy(Criterion):
    """One-hot-target cross entropy over probabilities
    (nn/CategoricalCrossEntropy.scala)."""

    def loss(self, output, target):
        logp = jnp.log(jnp.clip(output, 1e-8, 1.0))
        return _reduce(-jnp.sum(target * logp, axis=-1), True)


class SoftmaxWithCriterion(Criterion):
    """Softmax + NLL with optional ignore label, Caffe-style
    (nn/SoftmaxWithCriterion.scala). Input NCHW, target (N,1,H,W)."""

    def __init__(self, ignore_label=None, normalize_mode="VALID", name=None):
        super().__init__(name=name)
        self.ignore_label = ignore_label
        self.normalize_mode = normalize_mode

    def loss(self, output, target):
        logp = jax.nn.log_softmax(output, axis=1)
        t = target.astype(jnp.int32).reshape(
            target.shape[0], -1) - 1  # 1-based
        logp2 = jnp.moveaxis(logp, 1, -1).reshape(-1, logp.shape[1])
        tf = t.reshape(-1)
        valid = jnp.ones_like(tf, dtype=logp.dtype) if self.ignore_label is None \
            else (tf != self.ignore_label - 1).astype(logp.dtype)
        picked = jnp.take_along_axis(
            logp2, jnp.clip(tf, 0, logp.shape[1] - 1)[:, None], axis=-1)[:, 0]
        total = -jnp.sum(picked * valid)
        if self.normalize_mode == "VALID":
            return total / jnp.maximum(jnp.sum(valid), 1.0)
        if self.normalize_mode == "BATCH_SIZE":
            return total / output.shape[0]
        if self.normalize_mode == "FULL":
            return total / tf.shape[0]
        return total


class MSECriterion(Criterion):
    """mean (input - target)^2 (nn/MSECriterion.scala)."""
    def __init__(self, size_average=True, name=None):
        super().__init__(name=name)
        self.size_average = size_average

    def loss(self, output, target):
        return _reduce((output - target) ** 2, self.size_average)


class AbsCriterion(Criterion):
    """mean |input - target| (nn/AbsCriterion.scala)."""
    def __init__(self, size_average=True, name=None):
        super().__init__(name=name)
        self.size_average = size_average

    def loss(self, output, target):
        return _reduce(jnp.abs(output - target), self.size_average)


class BCECriterion(Criterion):
    """Binary cross entropy over probabilities (nn/BCECriterion.scala)."""

    def __init__(self, weights=None, size_average=True, name=None):
        super().__init__(name=name)
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average

    def loss(self, output, target):
        eps = 1e-12
        o = jnp.clip(output, eps, 1 - eps)
        per = -(target * jnp.log(o) + (1 - target) * jnp.log(1 - o))
        if self.weights is not None:
            per = per * self.weights
        return _reduce(per, self.size_average)


class SmoothL1Criterion(Criterion):
    """Huber loss: 0.5 d^2 if |d|<1 else |d|-0.5 (nn/SmoothL1Criterion.scala)."""
    def __init__(self, size_average=True, name=None):
        super().__init__(name=name)
        self.size_average = size_average

    def loss(self, output, target):
        d = jnp.abs(output - target)
        per = jnp.where(d < 1.0, 0.5 * d * d, d - 0.5)
        return _reduce(per, self.size_average)


class SmoothL1CriterionWithWeights(Criterion):
    """nn/SmoothL1CriterionWithWeights.scala (Fast-RCNN bbox loss).
    Input table target {t, inside_w, outside_w}."""

    def __init__(self, sigma=1.0, num=0, name=None):
        super().__init__(name=name)
        self.sigma2 = sigma * sigma
        self.num = num

    def loss(self, output, target):
        t, iw, ow = as_list(target)
        d = (output - t) * iw
        ad = jnp.abs(d)
        per = jnp.where(ad < 1.0 / self.sigma2,
                        0.5 * self.sigma2 * d * d, ad - 0.5 / self.sigma2)
        total = jnp.sum(per * ow)
        return total / self.num if self.num > 0 else total


class MarginCriterion(Criterion):
    """Hinge loss; targets +/-1 (nn/MarginCriterion.scala). squared=True
    gives squared hinge."""

    def __init__(self, margin=1.0, size_average=True, squared=False, name=None):
        super().__init__(name=name)
        self.margin = margin
        self.size_average = size_average
        self.squared = squared

    def loss(self, output, target):
        per = jnp.maximum(0.0, self.margin - output * target)
        if self.squared:
            per = per * per
        return _reduce(per, self.size_average)


class MarginRankingCriterion(Criterion):
    """max(0, -y*(x1-x2) + margin) over table inputs (nn/MarginRankingCriterion.scala)."""

    def __init__(self, margin=1.0, size_average=True, name=None):
        super().__init__(name=name)
        self.margin = margin
        self.size_average = size_average

    def loss(self, output, target):
        x1, x2 = as_list(output)
        y = jnp.asarray(as_list(target)[0])
        per = jnp.maximum(0.0, -y * (x1 - x2) + self.margin)
        return _reduce(per, self.size_average)


class HingeEmbeddingCriterion(Criterion):
    """x if y==1 else max(0, margin - x) (nn/HingeEmbeddingCriterion.scala)."""

    def __init__(self, margin=1.0, size_average=True, name=None):
        super().__init__(name=name)
        self.margin = margin
        self.size_average = size_average

    def loss(self, output, target):
        per = jnp.where(target == 1, output,
                        jnp.maximum(0.0, self.margin - output))
        return _reduce(per, self.size_average)


class L1HingeEmbeddingCriterion(Criterion):
    """L1 distance between pair; hinge on dissimilar pairs
    (nn/L1HingeEmbeddingCriterion.scala). Target is +1 (similar) or -1."""

    def __init__(self, margin=1.0, name=None):
        super().__init__(name=name)
        self.margin = margin

    def loss(self, output, target):
        x1, x2 = as_list(output)
        y = jnp.asarray(as_list(target)[0]).reshape(())
        d = jnp.sum(jnp.abs(x1 - x2))
        return jnp.where(y > 0, d, jnp.maximum(0.0, self.margin - d))


class CosineEmbeddingCriterion(Criterion):
    """1-cos(x1,x2) for y=1; max(0, cos-margin) for y=-1
    (nn/CosineEmbeddingCriterion.scala)."""

    def __init__(self, margin=0.0, size_average=True, name=None):
        super().__init__(name=name)
        self.margin = margin
        self.size_average = size_average

    def loss(self, output, target):
        x1, x2 = as_list(output)
        y = jnp.asarray(as_list(target)[0]).reshape(-1)
        cos = jnp.sum(x1 * x2, -1) / jnp.maximum(
            jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12)
        per = jnp.where(y > 0, 1.0 - cos,
                        jnp.maximum(0.0, cos - self.margin))
        return _reduce(per, self.size_average)


class CosineDistanceCriterion(Criterion):
    """1 - cos(x, target) (nn/CosineDistanceCriterion.scala)."""

    def __init__(self, size_average=True, name=None):
        super().__init__(name=name)
        self.size_average = size_average

    def loss(self, output, target):
        cos = jnp.sum(output * target, -1) / jnp.maximum(
            jnp.linalg.norm(output, axis=-1) * jnp.linalg.norm(target, axis=-1),
            1e-12)
        return _reduce(1.0 - cos, self.size_average)


class CosineProximityCriterion(Criterion):
    """-mean(cos of l2-normalized x,y) (nn/CosineProximityCriterion.scala)."""

    def loss(self, output, target):
        xn = output / jnp.maximum(
            jnp.linalg.norm(output, axis=-1, keepdims=True), 1e-12)
        yn = target / jnp.maximum(
            jnp.linalg.norm(target, axis=-1, keepdims=True), 1e-12)
        return -jnp.mean(jnp.sum(xn * yn, axis=-1))


class DistKLDivCriterion(Criterion):
    """KL(target || output) with output = log-probs (nn/DistKLDivCriterion.scala)."""

    def __init__(self, size_average=True, name=None):
        super().__init__(name=name)
        self.size_average = size_average

    def loss(self, output, target):
        per = jnp.where(target > 0, target * (jnp.log(
            jnp.maximum(target, 1e-12)) - output), 0.0)
        if self.size_average:
            return jnp.sum(per) / output.shape[0] if output.ndim > 1 \
                else jnp.mean(per)
        return jnp.sum(per)


class KLDCriterion(Criterion):
    """KL(N(mu, sigma^2) || N(0,1)) from table {mean, logvar}
    (nn/KLDCriterion.scala — VAE latent loss)."""

    def __init__(self, size_average=True, name=None):
        super().__init__(name=name)
        self.size_average = size_average

    def loss(self, output, target=None):
        mean, log_var = as_list(output)
        per = 0.5 * (mean ** 2 + jnp.exp(log_var) - 1.0 - log_var)
        return jnp.sum(per) / mean.shape[0] if self.size_average \
            else jnp.sum(per)


class GaussianCriterion(Criterion):
    """-log N(target; mean, exp(logvar)) from table {mean, logvar}
    (nn/GaussianCriterion.scala)."""

    def loss(self, output, target):
        mean, log_var = as_list(output)
        per = 0.5 * (np.log(2 * np.pi) + log_var
                     + (target - mean) ** 2 / jnp.exp(log_var))
        return jnp.sum(per)


class KullbackLeiblerDivergenceCriterion(Criterion):
    """KL over probability vectors, keras-style, inputs clipped
    (nn/KullbackLeiblerDivergenceCriterion.scala)."""

    def loss(self, output, target):
        y = jnp.clip(target, 1e-7, 1.0)
        p = jnp.clip(output, 1e-7, 1.0)
        return jnp.mean(jnp.sum(y * jnp.log(y / p), axis=-1))


class PoissonCriterion(Criterion):
    """mean(pred - target*log(pred)) (nn/PoissonCriterion.scala)."""

    def loss(self, output, target):
        return jnp.mean(output - target * jnp.log(jnp.maximum(output, 1e-7)))


class MeanAbsolutePercentageCriterion(Criterion):
    """mean |(target - input) / clip(|target|)| * 100 (nn/MeanAbsolutePercentageCriterion.scala)."""
    def loss(self, output, target):
        diff = jnp.abs(target - output) / jnp.clip(jnp.abs(target), 1e-7, None)
        return 100.0 * jnp.mean(diff)


class MeanSquaredLogarithmicCriterion(Criterion):
    """mean (log(target+1) - log(input+1))^2 (nn/MeanSquaredLogarithmicCriterion.scala)."""
    def loss(self, output, target):
        a = jnp.log(jnp.clip(output, 1e-7, None) + 1.0)
        b = jnp.log(jnp.clip(target, 1e-7, None) + 1.0)
        return jnp.mean((a - b) ** 2)


class MultiLabelMarginCriterion(Criterion):
    """Multi-label hinge (nn/MultiLabelMarginCriterion.scala): targets are
    1-based label indices padded with 0."""

    def __init__(self, size_average=True, name=None):
        super().__init__(name=name)
        self.size_average = size_average

    def loss(self, output, target):
        out2 = output.reshape(-1, output.shape[-1])
        t2 = target.astype(jnp.int32).reshape(-1, output.shape[-1])
        n, c = out2.shape
        t_idx = jnp.clip(t2 - 1, 0, c - 1)
        valid = (t2 > 0).astype(out2.dtype)  # (n, c)
        is_target = jnp.zeros((n, c), out2.dtype)
        is_target = jax.vmap(
            lambda it, ti, v: it.at[ti].add(v))(is_target, t_idx, valid)
        is_target = jnp.minimum(is_target, 1.0)
        tgt_scores = jnp.take_along_axis(out2, t_idx, axis=-1)  # (n, c)
        margins = 1.0 - tgt_scores[:, :, None] + out2[:, None, :]  # (n, c_t, c)
        mask = valid[:, :, None] * (1.0 - is_target[:, None, :])
        per = jnp.sum(jnp.maximum(margins, 0.0) * mask, axis=(1, 2)) / c
        return _reduce(per, self.size_average)


class MultiLabelSoftMarginCriterion(Criterion):
    """Sigmoid + BCE per label (nn/MultiLabelSoftMarginCriterion.scala)."""

    def __init__(self, weights=None, size_average=True, name=None):
        super().__init__(name=name)
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average

    def loss(self, output, target):
        per = (jax.nn.softplus(-output) * target
               + jax.nn.softplus(output) * (1 - target))
        if self.weights is not None:
            per = per * self.weights
        per = jnp.mean(per, axis=-1)
        return _reduce(per, self.size_average)


class MultiMarginCriterion(Criterion):
    """Multi-class hinge with 1-based integer target (nn/MultiMarginCriterion.scala)."""

    def __init__(self, p=1, weights=None, margin=1.0, size_average=True,
                 name=None):
        super().__init__(name=name)
        self.p = p
        self.weights = None if weights is None else jnp.asarray(weights)
        self.margin = margin
        self.size_average = size_average

    def loss(self, output, target):
        out2 = output.reshape(-1, output.shape[-1])
        t = target.astype(jnp.int32).reshape(-1) - 1
        n, c = out2.shape
        tgt = jnp.take_along_axis(out2, t[:, None], axis=-1)
        margins = jnp.maximum(0.0, self.margin - tgt + out2) ** self.p
        if self.weights is not None:
            margins = margins * jnp.take(self.weights, t)[:, None]
        margins = margins * (1 - jax.nn.one_hot(t, c, dtype=out2.dtype))
        per = jnp.sum(margins, axis=-1) / c
        return _reduce(per, self.size_average)


class SoftMarginCriterion(Criterion):
    """mean(log(1+exp(-y*x))) (nn/SoftMarginCriterion.scala)."""

    def __init__(self, size_average=True, name=None):
        super().__init__(name=name)
        self.size_average = size_average

    def loss(self, output, target):
        return _reduce(jax.nn.softplus(-output * target), self.size_average)


class ClassSimplexCriterion(Criterion):
    """MSE against simplex embedding of the (1-based) class
    (nn/ClassSimplexCriterion.scala)."""

    def __init__(self, n_classes, name=None):
        super().__init__(name=name)
        self.n_classes = n_classes
        # regular simplex embedding in R^n: identity shifted so the n
        # vertices are equidistant (closed form, equivalent to the
        # reference's gram-schmidt construction up to rotation)
        a = (1.0 - np.sqrt(1.0 + n_classes)) / n_classes
        m = np.eye(n_classes, dtype=np.float32) + a / np.sqrt(n_classes)
        self.simplex = jnp.asarray(m)

    def loss(self, output, target):
        t = target.astype(jnp.int32).reshape(-1) - 1
        goal = jnp.take(self.simplex, t, axis=0)
        return jnp.mean((output - goal) ** 2)


class DiceCoefficientCriterion(Criterion):
    """1 - dice overlap (nn/DiceCoefficientCriterion.scala)."""

    def __init__(self, size_average=True, epsilon=1.0, name=None):
        super().__init__(name=name)
        self.epsilon = epsilon

    def loss(self, output, target):
        o = output.reshape(output.shape[0], -1)
        t = target.reshape(target.shape[0], -1)
        inter = jnp.sum(o * t, axis=-1)
        union = jnp.sum(o, axis=-1) + jnp.sum(t, axis=-1)
        dice = (2 * inter + self.epsilon) / (union + self.epsilon)
        return jnp.mean(1.0 - dice)


class L1Cost(Criterion):
    """sum |x| (nn/L1Cost.scala)."""

    def loss(self, output, target=None):
        return jnp.sum(jnp.abs(output))


class DotProductCriterion(Criterion):
    """-sum(x * target) — maximizing dot product (nn/DotProductCriterion.scala
    computes sum(x*y) as the loss with positive grad; sign preserved)."""

    def __init__(self, size_average=False, name=None):
        super().__init__(name=name)
        self.size_average = size_average

    def loss(self, output, target):
        return _reduce(output * target, self.size_average)


class PGCriterion(Criterion):
    """Policy-gradient criterion (nn/PGCriterion.scala): -sum(log(p) * reward)
    with input probabilities (or log-probs)."""

    def __init__(self, size_average=False, name=None):
        super().__init__(name=name)
        self.size_average = size_average

    def loss(self, output, target):
        logp = jnp.log(jnp.maximum(output, 1e-8))
        return _reduce(-logp * target, self.size_average)


class MultiCriterion(Criterion):
    """Weighted sum of criterions on the same (input, target)
    (nn/MultiCriterion.scala)."""

    def __init__(self, name=None):
        super().__init__(name=name)
        self.criterions = []
        self.weights = []

    def add(self, criterion, weight=1.0):
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def loss(self, output, target):
        return sum(w * c.loss(output, target)
                   for c, w in zip(self.criterions, self.weights))


class ParallelCriterion(Criterion):
    """i-th criterion applied to i-th (input, target) table element
    (nn/ParallelCriterion.scala)."""

    def __init__(self, repeat_target=False, name=None):
        super().__init__(name=name)
        self.repeat_target = repeat_target
        self.criterions = []
        self.weights = []

    def add(self, criterion, weight=1.0):
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def loss(self, output, target):
        outs = as_list(output)
        tgts = [target] * len(outs) if self.repeat_target else as_list(target)
        return sum(w * c.loss(o, t) for c, w, o, t in
                   zip(self.criterions, self.weights, outs, tgts))


class TimeDistributedCriterion(Criterion):
    """Apply a criterion at every timestep of (B, T, ...) input
    (nn/TimeDistributedCriterion.scala)."""

    def __init__(self, critrn, size_average=False, dimension=2, name=None):
        super().__init__(name=name)
        self.critrn = critrn
        self.size_average = size_average
        self.dimension = dimension

    def loss(self, output, target):
        ax = self.dimension - 1
        n = output.shape[ax]
        total = 0.0
        o_parts = jnp.split(output, n, axis=ax)
        t_parts = jnp.split(target, n, axis=ax)
        for o, t in zip(o_parts, t_parts):
            total = total + self.critrn.loss(jnp.squeeze(o, axis=ax),
                                             jnp.squeeze(t, axis=ax))
        return total / n if self.size_average else total


class TimeDistributedMaskCriterion(Criterion):
    """Time-distributed criterion skipping padded targets
    (nn/TimeDistributedMaskCriterion.scala). Supported for ClassNLL inner."""

    def __init__(self, critrn, padding_value=0, name=None):
        super().__init__(name=name)
        self.critrn = critrn
        self.padding_value = padding_value

    def loss(self, output, target):
        inner = ClassNLLCriterion(
            size_average=True, padding_value=self.padding_value,
            log_prob_as_input=getattr(self.critrn, "log_prob_as_input", True))
        return inner.loss(output.reshape(-1, output.shape[-1]),
                          target.reshape(-1))


class TransformerCriterion(Criterion):
    """Apply transformations to input/target before an inner criterion
    (nn/TransformerCriterion.scala)."""

    def __init__(self, criterion, input_transformer=None,
                 target_transformer=None, name=None):
        super().__init__(name=name)
        self.criterion = criterion
        self.input_transformer = input_transformer
        self.target_transformer = target_transformer

    def loss(self, output, target):
        if self.input_transformer is not None:
            t = self.input_transformer
            t.ensure_initialized()  # respects weights loaded onto the module
            output, _ = t.run(t._params, output, state=t._state)
        if self.target_transformer is not None:
            t = self.target_transformer
            t.ensure_initialized()
            target, _ = t.run(t._params, target, state=t._state)
        return self.criterion.loss(output, target)
