"""Data-dependent control flow as modules.

The reference builds these as graph-node clusters interpreted at
runtime: ``ControlNodes.whileLoop`` wires Enter/Merge/LoopCondition/
Switch/NextIteration/Exit nodes (nn/tf/ControlOps.scala:296) which
``FrameManager`` (nn/FrameManager.scala:31) schedules inside a
``DynamicGraph``; ``ControlNodes.switch``/``merge`` (:245, :261) give
data-dependent branching, and ``DynamicGraph.backward``
(nn/DynamicGraph.scala:62, generateBackward :32) differentiates through
the control clusters.  The TPU-native equivalents compile the whole
construct into the XLA program instead:

  * :class:`WhileLoop` — ``lax.while_loop`` over a Table of loop vars;
    with ``max_iters=N`` it lowers to a bounded ``lax.scan`` with an
    active-mask carry, which IS reverse-differentiable (the TPU-native
    answer to DynamicGraph.generateBackward).
  * :class:`Cond`      — ``lax.cond`` over two branches

(The same lowering the TF importer applies to frame clusters found in
imported GraphDefs — utils/tf_import.py ``_rewrite_while_frames``.)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.table import Table, as_list
from .module import Ctx, Module

__all__ = ["WhileLoop", "Cond", "bounded_while"]


def bounded_while(cond_fn, body_fn, init, max_iters):
    """``while cond_fn(state): state = body_fn(state)`` as a bounded
    ``lax.scan`` with a sticky active mask — the reverse-differentiable
    lowering shared by :class:`WhileLoop` (``max_iters=``) and the TF
    importer's trained loops (utils/tf_import.py).  ``state`` is a tuple
    of arrays; once ``cond_fn`` goes false the state freezes, so the
    result equals the unbounded loop whenever it terminates within
    ``max_iters`` (beyond that it is truncated).  All ``max_iters``
    body iterations are computed (masked) every call."""
    def step(carry, _):
        state, active = carry
        # while semantics: test cond on the CURRENT state, then run the
        # body only while still active; once inactive the state freezes
        # (cond re-evaluates false on the frozen state, and `active` is
        # sticky anyway).  The freeze is a lax.cond, NOT a jnp.where
        # over an always-executed body: on the frozen terminal state the
        # body may compute non-finite values (sqrt of a negative, ...)
        # and where's untaken branch still leaks 0*NaN=NaN into the VJP;
        # cond executes (and differentiates) only the taken branch.
        active = jnp.logical_and(active, cond_fn(state))
        state = lax.cond(
            active,
            lambda s: tuple(jnp.asarray(v) for v in body_fn(s)),
            lambda s: s,
            state)
        return (state, active), None

    (final, _), _ = lax.scan(step, (tuple(init), jnp.bool_(True)), None,
                             length=int(max_iters))
    return final


def _as_tuple(x):
    return tuple(as_list(x)) if isinstance(x, Table) else (x,)


def _pack(vals, like):
    return Table(*vals) if isinstance(like, Table) or len(vals) > 1 \
        else vals[0]


class WhileLoop(Module):
    """``while cond(state): state = body(state)`` compiled to ONE
    ``lax.while_loop`` (≙ ControlNodes.whileLoop + the FrameManager
    runtime).  ``cond`` maps the loop-state (Table or tensor) to a
    boolean scalar; ``body`` maps state to the next state with the same
    shapes/dtypes.  The input activation is the initial state; the
    output is the final state.

    Two lowerings:

    * ``max_iters=None`` (default): ``lax.while_loop`` — unbounded trip
      count, but XLA's while is not reverse-differentiable; use inside
      inference / non-gradient paths.
    * ``max_iters=N``: a bounded ``lax.scan`` over N steps carrying an
      active mask — each step freezes the state once ``cond`` goes
      false, so the result equals the unbounded loop whenever it
      terminates within N iterations (beyond N it is truncated).  The
      scan IS reverse-differentiable: gradients flow through exactly
      the iterations that executed, matching the reference's
      DynamicGraph backward over control clusters
      (nn/DynamicGraph.scala:62).  Cost: all N body iterations are
      always computed (masked), so pick N near the real trip bound.

    ``cond``/``body`` must be stateless (no BN running stats inside).
    """

    def __init__(self, cond, body, max_iters=None, name=None):
        super().__init__(name=name)
        self.cond = cond
        self.body = body
        self.max_iters = max_iters

    def children(self):
        return [self.cond, self.body]

    def _serde_restore_children(self, children):
        self.cond, self.body = children

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        p = {}
        p.update(self.cond.init(k1))
        p.update(self.body.init(k2))
        return p

    def initial_state(self):
        st = {}
        st.update(self.cond.initial_state())
        st.update(self.body.initial_state())
        return st

    def apply(self, params, x, ctx):
        init = tuple(jnp.asarray(v) for v in _as_tuple(x))

        def sub_ctx():
            return Ctx(state=ctx.state, training=ctx.training,
                       rng_key=ctx.rng_key)

        def c(state):
            out = self.cond.apply(params, _pack(state, x), sub_ctx())
            return jnp.reshape(out, ())

        def b(state):
            out = self.body.apply(params, _pack(state, x), sub_ctx())
            return tuple(jnp.asarray(v) for v in _as_tuple(out))

        if self.max_iters is None:
            final = lax.while_loop(c, b, init)
        else:
            final = bounded_while(c, b, init, self.max_iters)
        return _pack(final, x)


class Cond(Module):
    """``pred(x) ? true_branch(x) : false_branch(x)`` compiled to
    ``lax.cond`` — only the taken branch executes (≙ the reference's
    ControlNodes.switch/merge pair, SwitchOps/MergeOps in
    nn/tf/ControlOps.scala).  Differentiable; both branches must return
    matching shapes/dtypes.

    Training-mode state writes (BN running stats) and side losses
    raised INSIDE a branch propagate out whenever the two branches'
    carries can be merged into one ``lax.cond`` output: state writes
    are unioned (a key only one branch writes falls back to its
    current persistent value on the other side, so shapes match), and
    side-loss lists are zero-padded to a common length.  When merging
    is impossible (e.g. a branch writes state with no current value to
    fall back on, or side losses of mismatched shapes), those effects
    are dropped inside the branches — the pre-round-5 behavior — and
    only the branch output propagates.  ``pred`` runs outside the cond
    with the real ctx, so its effects always propagate."""

    def __init__(self, pred, true_branch, false_branch, name=None):
        super().__init__(name=name)
        self.pred = pred
        self.true_branch = true_branch
        self.false_branch = false_branch

    def children(self):
        return [self.pred, self.true_branch, self.false_branch]

    def _serde_restore_children(self, children):
        self.pred, self.true_branch, self.false_branch = children

    def init(self, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        p = {}
        p.update(self.pred.init(k1))
        p.update(self.true_branch.init(k2))
        p.update(self.false_branch.init(k3))
        return p

    def initial_state(self):
        st = {}
        for m in (self.pred, self.true_branch, self.false_branch):
            st.update(m.initial_state())
        return st

    def apply(self, params, x, ctx):
        # pred runs OUTSIDE lax.cond: its state writes / side losses
        # propagate through the real ctx
        p = jnp.reshape(self.pred.apply(params, x, ctx), ())

        def capture(branch):
            """Branch fn returning (out, new_state, side_losses)."""
            def f(v):
                c = Ctx(state=ctx.state, training=ctx.training,
                        rng_key=ctx.rng_key)
                out = branch.apply(params, v, c)
                return out, dict(c.new_state), tuple(c.side_losses)
            return f

        f_t = capture(self.true_branch)
        f_f = capture(self.false_branch)
        # fallback values come from the EFFECTIVE current state (an
        # earlier same-named module's write in this forward must not be
        # clobbered with the pre-forward value)
        eff_state = {**ctx.state, **ctx.new_state}
        plan = self._merge_plan(f_t, f_f, x, eff_state, ctx)
        if plan is None:
            # unmergeable carries: branch-internal effects are dropped
            return lax.cond(p, lambda v: f_t(v)[0],
                            lambda v: f_f(v)[0], x)
        union, pads = plan
        tu = jax.tree_util

        def wrap(f):
            def g(v):
                out, new_state, losses = f(v)
                merged = {
                    k: tu.tree_map(jnp.asarray,
                                   new_state[k] if k in new_state
                                   else eff_state[k])
                    for k in union}
                losses = tuple(losses) + tuple(
                    jnp.zeros(shape, dtype)
                    for shape, dtype in pads[len(losses):])
                return out, merged, losses
            return g

        out, new_state, losses = lax.cond(p, wrap(f_t), wrap(f_f), x)
        ctx.new_state.update(new_state)
        ctx.side_losses.extend(losses)
        return out

    def _merge_plan(self, f_t, f_f, x, eff_state, ctx):
        """(union_keys, loss_pad_shapes) when the two branches' carries
        can be merged into one lax.cond output, else None.  The decision
        depends only on branch structure and input/state shapes, so it
        is cached per (training, rng, input-shape) signature — the two
        eval_shape traces run once, not on every eager forward."""
        tu = jax.tree_util
        cache = getattr(self, "_merge_plan_cache", None)
        if cache is None:
            cache = self._merge_plan_cache = {}
        try:
            key = (bool(ctx.training), ctx.rng_key is None,
                   tu.tree_structure(x),
                   tuple((tuple(jnp.shape(l)), jnp.result_type(l).name)
                         for l in tu.tree_leaves(x)))
        except Exception:
            key = None
        if key is not None and key in cache:
            plan = cache[key]
            # cheap revalidation: every fallback key must still exist
            if plan is None or all(k in eff_state for k in plan[0]):
                return plan
        plan, stable = self._compute_merge_plan(f_t, f_f, x, eff_state)
        # only cache outcomes that depend purely on branch structure +
        # input signature ("merge" and "no effects at all"); a None from
        # a transiently incomplete state dict or an eval_shape hiccup
        # must not permanently disable effect propagation
        if key is not None and stable:
            cache[key] = plan
        return plan

    @staticmethod
    def _compute_merge_plan(f_t, f_f, x, eff_state):
        """Returns (plan, stable): plan is (union, pads) or None; stable
        says whether the outcome may be cached for this signature."""
        tu = jax.tree_util

        def struct_eq(have, want):
            """`have` (arrays) matches `want` (ShapeDtypeStructs)?"""
            try:
                flags = tu.tree_map(
                    lambda a, w: jnp.shape(a) == tuple(w.shape)
                    and jnp.result_type(a) == w.dtype, have, want)
            except ValueError:          # tree structure mismatch
                return False
            return all(tu.tree_leaves(flags))

        try:
            _, st_t, ls_t = jax.eval_shape(f_t, x)
            _, st_f, ls_f = jax.eval_shape(f_f, x)
        except Exception:
            return None, False          # transient: retry next call
        if not (st_t or st_f or ls_t or ls_f):
            return None, True    # structurally nothing to merge — cache

        union = sorted(set(st_t) | set(st_f))
        for k in union:
            if k in st_t and k in st_f:
                # both write: carries must agree shape/dtype-wise
                ok = tu.tree_structure(st_t[k]) == tu.tree_structure(
                    st_f[k]) and all(tu.tree_leaves(tu.tree_map(
                        lambda a, b: a.shape == b.shape
                        and a.dtype == b.dtype, st_t[k], st_f[k])))
                if not ok:
                    return None, True   # structural mismatch — cache
            else:
                # one-sided write: the other side falls back to the
                # key's CURRENT effective value, which must exist and
                # match the writing branch's shapes.  State contents
                # vary call to call, so this outcome is NOT cacheable.
                want = st_t[k] if k in st_t else st_f[k]
                if k not in eff_state or not struct_eq(eff_state[k],
                                                       want):
                    return None, False

        # side losses pair positionally; the shorter list zero-pads
        for a, b in zip(ls_t, ls_f):
            if a.shape != b.shape or a.dtype != b.dtype:
                return None, True       # structural mismatch — cache
        longer = ls_t if len(ls_t) >= len(ls_f) else ls_f
        pads = tuple((tuple(s.shape), s.dtype) for s in longer)
        # a union with one-sided-write keys built its fallbacks from the
        # CURRENT eff_state contents — a later call may carry differently
        # shaped state for the same input signature, so such plans are
        # recomputed per call (symmetric with the (None, False) above)
        one_sided = any(k not in st_t or k not in st_f for k in union)
        return (union, pads), not one_sided
