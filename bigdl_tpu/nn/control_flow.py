"""Data-dependent control flow as modules.

The reference builds these as graph-node clusters interpreted at
runtime: ``ControlNodes.whileLoop`` wires Enter/Merge/LoopCondition/
Switch/NextIteration/Exit nodes (nn/tf/ControlOps.scala:296) which
``FrameManager`` (nn/FrameManager.scala:31) schedules inside a
``DynamicGraph``; ``ControlNodes.switch``/``merge`` (:245, :261) give
data-dependent branching.  The TPU-native equivalents compile the whole
construct into the XLA program instead:

  * :class:`WhileLoop` — ``lax.while_loop`` over a Table of loop vars
  * :class:`Cond`      — ``lax.cond`` over two branches

(The same lowering the TF importer applies to frame clusters found in
imported GraphDefs — utils/tf_import.py ``_rewrite_while_frames``.)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.table import Table, as_list
from .module import Ctx, Module

__all__ = ["WhileLoop", "Cond"]


def _as_tuple(x):
    return tuple(as_list(x)) if isinstance(x, Table) else (x,)


def _pack(vals, like):
    return Table(*vals) if isinstance(like, Table) or len(vals) > 1 \
        else vals[0]


class WhileLoop(Module):
    """``while cond(state): state = body(state)`` compiled to ONE
    ``lax.while_loop`` (≙ ControlNodes.whileLoop + the FrameManager
    runtime).  ``cond`` maps the loop-state (Table or tensor) to a
    boolean scalar; ``body`` maps state to the next state with the same
    shapes/dtypes.  The input activation is the initial state; the
    output is the final state.

    XLA's while is not reverse-differentiable — use inside inference /
    non-gradient paths, or under ``lax.stop_gradient`` semantics (the
    reference's dynamic graphs were likewise inference-oriented).
    ``cond``/``body`` must be stateless (no BN running stats inside).
    """

    def __init__(self, cond, body, name=None):
        super().__init__(name=name)
        self.cond = cond
        self.body = body

    def children(self):
        return [self.cond, self.body]

    def _serde_restore_children(self, children):
        self.cond, self.body = children

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        p = {}
        p.update(self.cond.init(k1))
        p.update(self.body.init(k2))
        return p

    def initial_state(self):
        st = {}
        st.update(self.cond.initial_state())
        st.update(self.body.initial_state())
        return st

    def apply(self, params, x, ctx):
        init = tuple(jnp.asarray(v) for v in _as_tuple(x))

        def sub_ctx():
            return Ctx(state=ctx.state, training=ctx.training,
                       rng_key=ctx.rng_key)

        def c(state):
            out = self.cond.apply(params, _pack(state, x), sub_ctx())
            return jnp.reshape(out, ())

        def b(state):
            out = self.body.apply(params, _pack(state, x), sub_ctx())
            return tuple(jnp.asarray(v) for v in _as_tuple(out))

        final = lax.while_loop(c, b, init)
        return _pack(final, x)


class Cond(Module):
    """``pred(x) ? true_branch(x) : false_branch(x)`` compiled to
    ``lax.cond`` — only the taken branch executes (≙ the reference's
    ControlNodes.switch/merge pair, SwitchOps/MergeOps in
    nn/tf/ControlOps.scala).  Differentiable; both branches must return
    matching shapes/dtypes.

    The branches run inside the ``lax.cond`` trace, so training-mode
    state writes (BN running stats) and side losses raised INSIDE a
    branch do not propagate out — the two branches' state trees would
    have to match structurally for a merged carry.  Branch children may
    still READ persistent state (eval-mode BN works); keep stat-updating
    training layers outside the branches.  ``pred`` runs outside the
    cond with the real ctx."""

    def __init__(self, pred, true_branch, false_branch, name=None):
        super().__init__(name=name)
        self.pred = pred
        self.true_branch = true_branch
        self.false_branch = false_branch

    def children(self):
        return [self.pred, self.true_branch, self.false_branch]

    def _serde_restore_children(self, children):
        self.pred, self.true_branch, self.false_branch = children

    def init(self, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        p = {}
        p.update(self.pred.init(k1))
        p.update(self.true_branch.init(k2))
        p.update(self.false_branch.init(k3))
        return p

    def initial_state(self):
        st = {}
        for m in (self.pred, self.true_branch, self.false_branch):
            st.update(m.initial_state())
        return st

    def apply(self, params, x, ctx):
        def sub_ctx():
            return Ctx(state=ctx.state, training=ctx.training,
                       rng_key=ctx.rng_key)

        # pred runs OUTSIDE lax.cond: its state writes / side losses
        # propagate through the real ctx
        p = jnp.reshape(self.pred.apply(params, x, ctx), ())
        return lax.cond(
            p,
            lambda v: self.true_branch.apply(params, v, sub_ctx()),
            lambda v: self.false_branch.apply(params, v, sub_ctx()),
            x)
