"""GSPMD trainer for the transformer flagship: dp × fsdp × tp × sp.

The DistriOptimizer (optim/distri_optimizer.py) mirrors the reference's
parameter-server loop with explicit shard_map collectives; this module is
the complementary *compiler-partitioned* path — the idiomatic TPU recipe:

  1. pick a Mesh (parallel/mesh.py), e.g. {'dp': 2, 'fsdp': 2, 'tp': 2}
  2. place parameters with NamedShardings (tp layout declared per-module
     via ``pspec``; an 'fsdp' dimension is layered onto the first free,
     divisible axis of every large parameter — ZeRO-3 by sharding alone)
  3. jit the whole train step and let the XLA partitioner insert the
     collectives (all-gather for fsdp params, psum after row-parallel
     matmuls, reduce-scatter in the backward)
  4. the one manual island: ring attention over 'sp' via shard_map
     (parallel/ring_attention.py), wired into MultiHeadAttention.

Optimizer state sharding is *propagated*, not spelled out: ``init_state``
is jitted with sharded params, so every moment tensor inherits its
parameter's sharding.
"""
from __future__ import annotations

import time
from functools import partial
from types import SimpleNamespace
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib
from .ring_attention import ring_attention_shmap
from ..models.transformer import TransformerLM
from ..observability import collectives as _acct
from ..observability import (DivergenceError, Recorder, null_recorder,
                             set_recorder)
from ..optim.optimizer import make_accum_grads


def _filter_spec(spec: P, mesh: Mesh) -> P:
    """Drop axis names the mesh does not have."""
    def keep(e):
        if e is None:
            return None
        if isinstance(e, (tuple, list)):
            kept = tuple(a for a in e if a in mesh.axis_names)
            return kept if kept else None
        return e if e in mesh.axis_names else None
    return P(*(keep(e) for e in spec))


def _add_axis(spec: P, shape, mesh: Mesh, axis: str,
              min_size: int = 2 ** 16) -> P:
    """Layer ``axis`` onto the first free, divisible dim of a large
    param — the one sharding-layering rule ('fsdp' onto params, 'dp'
    onto optimizer moments for the zero1 annotation)."""
    if axis not in mesh.axis_names or int(np.prod(shape)) < min_size:
        return spec
    n = mesh.shape[axis]
    entries = list(spec) + [None] * (len(shape) - len(spec))
    for i, (e, s) in enumerate(zip(entries, shape)):
        if e is None and s % n == 0:
            entries[i] = axis
            break
    return P(*entries)


def _add_fsdp(spec: P, shape, mesh: Mesh, min_size: int = 2 ** 16) -> P:
    """Layer 'fsdp' onto the first free, divisible dim of a large param."""
    return _add_axis(spec, shape, mesh, "fsdp", min_size)


class SpmdTrainer:
    """Compiles one fused (fwd + bwd + update) XLA program over the mesh."""

    def __init__(self, model: TransformerLM, optim, mesh: Optional[Mesh] = None,
                 fsdp: bool = True, seed: int = 0,
                 ring_attention: Optional[bool] = None,
                 min_fsdp_size: int = 2 ** 16, grad_accum: int = 1,
                 loss_chunk: Optional[int] = None, zero1: bool = False,
                 zero1_min_size: Optional[int] = None):
        self.model = model
        self.optim = optim
        self.mesh = mesh or mesh_lib.get_mesh()
        self.seed = seed
        self.min_fsdp_size = min_fsdp_size
        # ZeRO-1 by ANNOTATION (arXiv:2004.13336 — "automatic
        # cross-replica sharding of weight update"): optimizer moments
        # get 'dp' layered onto their first free, divisible dim via
        # sharding metadata, and a with_sharding_constraint pins the
        # updated state to the same layout — the GSPMD partitioner then
        # shards the elementwise update math 1/dp and inserts the
        # collectives itself.  Composes with tp (megatron pspecs) and
        # fsdp (moments already carry the param's fsdp dim; dp lands on
        # a different free dim).  Memory claim is enforced by the
        # sharding metadata, inspectable on opt_state leaves.
        if zero1 and self.mesh.shape.get("dp", 1) < 2:
            raise ValueError("zero1 shards the update over the dp axis: "
                             "the mesh needs dp > 1")
        self.zero1 = bool(zero1)
        self.zero1_min_size = (min_fsdp_size if zero1_min_size is None
                               else int(zero1_min_size))
        cfg = model.cfg
        if ring_attention is None:
            ring_attention = cfg.use_ring_attention
        self.ring = bool(ring_attention and "sp" in self.mesh.axis_names
                         and self.mesh.shape.get("sp", 1) > 1)
        self.fsdp = fsdp and "fsdp" in self.mesh.axis_names
        self._batch_axes = tuple(a for a in ("dp", "fsdp")
                                 if a in self.mesh.axis_names)
        self._seq_axis = "sp" if "sp" in self.mesh.axis_names else None
        self.grad_accum = int(grad_accum)
        # chunked head+loss: caps logits memory at (B, chunk, V) — see
        # TransformerLM.token_nll.  None = single full-sequence projection.
        self.loss_chunk = loss_chunk
        self.params = None
        self.opt_state = None
        self._step_fn = None
        self._step_count = 0
        self._recorder = None
        self._trace_ctx = None          # TraceContext from the supervisor
        self._tracer = None             # None -> process default
        self._telemetry_health = True
        self._with_health = False
        self._hlo_accounted = False
        self._seen_sigs = set()
        # static cost capture (observability.profile), once per init()
        self._capture_cost = True
        self._cost_pending = False
        self._ckpt_layout = "orbax"
        self._ckpt_mgr = None
        self._shard_arrays = False      # elastic sliced saves (v2)
        self._preemption = None
        # device-side input transform compiled into the step (the
        # uint8-wire / device-augment hook for this path)
        self._input_transform = None
        # attached streaming dataset whose cursor rides in checkpoints
        self._data_pipeline = None
        # training-health layer (observability.health)
        self._health_monitor = None
        self._flight = None
        self._watchdog = None
        self._http_server = None
        self._max_rollbacks = 2

    # ------------------------------------------------------------------ #
    def _param_shardings(self, params):
        specs = self.model.param_pspecs(params)
        by_name = {m.name: m for m in self.model.modules()}
        out = {}
        for mod, sub in params.items():
            # modules may opt out of fsdp layering (fsdp_exempt=True):
            # the token embedding must, because layering 'fsdp' onto its
            # free dim makes the gather+residual pattern miscompile on
            # the GSPMD partitioner AND costs two involuntary-full-remat
            # reshards of its cotangent — see TokenEmbedding's note and
            # tests/test_partitioner_repro.py
            exempt = getattr(by_name.get(mod), "fsdp_exempt", False)
            out[mod] = {}
            for k, p in sub.items():
                spec = _filter_spec(specs[mod][k], self.mesh)
                if self.fsdp and not exempt:
                    spec = _add_fsdp(spec, p.shape, self.mesh,
                                     self.min_fsdp_size)
                out[mod][k] = NamedSharding(self.mesh, spec)
        return out

    def _zero1_opt_shardings(self, params, shardings, opt_state):
        """Per-leaf NamedShardings for the zero1-annotated optimizer
        state, as ``{leaf path: NamedSharding}`` for exactly the leaves
        the annotation touches: a moment leaf whose tree-path suffix
        names an existing param (and matches its shape) takes that
        param's spec with 'dp' layered onto the first free divisible
        dim.  Scalars and unmatched leaves are absent — they keep the
        (uncommitted) placement init gave them, so jit dispatch stays
        free to move them.  Path correspondence, not shape matching —
        the ``fsdp_opt_state_specs`` rule."""
        p_paths, _ = jax.tree_util.tree_flatten_with_path(params)
        sh_leaves = jax.tree_util.tree_leaves(
            shardings, is_leaf=lambda v: hasattr(v, "spec"))
        by_path = {tuple(path): (tuple(leaf.shape), sh.spec)
                   for (path, leaf), sh in zip(p_paths, sh_leaves)}

        out = {}

        def for_leaf(path, leaf):
            shape = tuple(getattr(leaf, "shape", ()))
            for i in range(len(path)):
                hit = by_path.get(tuple(path[i:]))
                if hit is not None and hit[0] == shape:
                    spec = _add_axis(hit[1], shape, self.mesh, "dp",
                                     self.zero1_min_size)
                    out[tuple(path)] = NamedSharding(self.mesh, spec)
                    return leaf

        jax.tree_util.tree_map_with_path(for_leaf, opt_state)
        return out

    def _batch_sharding(self):
        ba = self._batch_axes
        lead = ba if len(ba) > 1 else (ba[0] if ba else None)
        return NamedSharding(self.mesh, P(lead, self._seq_axis))

    # ------------------------------------------------------------------ #
    def attach(self):
        """Wire the sp ring into the model's attention modules (rebinding
        any hook a previous trainer left), remembering the old hooks so
        :meth:`detach` can restore standalone/other-mesh use of the model."""
        if not self.ring:
            return self
        fn = partial(ring_attention_shmap, mesh=self.mesh, causal=True)
        for blk in self.model.blocks:
            cur = blk.attn.attention_fn
            # stash the model's TRUE original on the module itself; never
            # stash another trainer's ring hook (interleaved trainers would
            # otherwise "restore" a foreign mesh's ring fn on detach)
            if not (isinstance(cur, partial)
                    and cur.func is ring_attention_shmap):
                blk.attn._pre_ring_attention_fn = cur
            blk.attn.attention_fn = fn
        self._attached = True
        return self

    def detach(self):
        """Restore the model's original attention hooks (pre any ring)."""
        if getattr(self, "_attached", False):
            for blk in self.model.blocks:
                if hasattr(blk.attn, "_pre_ring_attention_fn"):
                    blk.attn.attention_fn = blk.attn._pre_ring_attention_fn
            self._attached = False
        return self

    def init(self):
        self.attach()
        params = self.model.init(jax.random.PRNGKey(self.seed))
        shardings = self._param_shardings(params)
        self.params = jax.tree_util.tree_map(jax.device_put, params, shardings)
        # jitted with sharded params -> moments inherit the param shardings
        self.opt_state = jax.jit(self.optim.init_state)(self.params)
        zero1_sh = None
        if self.zero1:
            zero1_sh = self._zero1_opt_shardings(params, shardings,
                                                 self.opt_state)
            self.opt_state = jax.tree_util.tree_map_with_path(
                lambda path, leaf: jax.device_put(
                    leaf, zero1_sh[tuple(path)])
                if tuple(path) in zero1_sh else leaf, self.opt_state)
        model, optim = self.model, self.optim

        n_accum = self.grad_accum

        loss_chunk = self.loss_chunk

        def loss_fn(p, tokens, targets, rng):
            from ..nn.module import Ctx
            ctx = Ctx(state={}, training=True, rng_key=rng)
            loss = model.loss(p, tokens, targets, loss_chunk=loss_chunk,
                              ctx=ctx)
            for sl in ctx.side_losses:   # e.g. MoE load-balancing aux
                loss = loss + sl
            return loss

        # model.loss is a MASKED token mean, so microbatches are
        # weighted by their valid-token count (equal weighting would
        # misweight padded batches — see make_accum_grads)
        grads_fn = make_accum_grads(
            lambda p, s, t, y, r: (loss_fn(p, t, y, r), s), n_accum,
            weight_fn=lambda t, y: (y != -1).sum())

        from ..optim.optimizer import health_scalars, mask_frozen_grads

        telemetry = self._telemetry_active()
        self._with_health = telemetry
        self._seen_sigs.clear()
        transform = self._input_transform

        def step(params, opt_state, tokens, targets, rng):
            if transform is not None:
                # traced-rng split only (GL005: no host state in the
                # trace); the transform fuses into the step program
                rng, t_rng = jax.random.split(rng)
                tokens = transform(tokens, t_rng)
            (loss, _), grads = grads_fn(params, {}, tokens, targets, rng)
            grads = mask_frozen_grads(model, grads)
            new_params, new_opt = optim.update(grads, params, opt_state)
            if zero1_sh is not None:
                # pin the updated state to the 1/dp layout: without the
                # constraint the partitioner may re-replicate moments to
                # match the (replicated-over-dp) grads, silently undoing
                # the memory win the annotation promises
                new_opt = jax.tree_util.tree_map_with_path(
                    lambda path, x: jax.lax.with_sharding_constraint(
                        x, zero1_sh[tuple(path)])
                    if tuple(path) in zero1_sh else x, new_opt)
            if telemetry:
                # global arrays under full-auto jit: the norm reductions
                # are already global, no explicit collective needed
                return (new_params, new_opt, loss,
                        health_scalars(grads, params, new_params))
            return new_params, new_opt, loss

        self._step_fn = jax.jit(step, donate_argnums=(0, 1))
        self._cost_pending = True   # new program: re-capture its cost
        return self

    # -- telemetry ------------------------------------------------------- #
    def set_telemetry(self, recorder, health: bool = True,
                      capture_cost: bool = True):
        """Attach an observability Recorder: each step() emits a step
        record (spans: h2d / train_step with compile detection; scalars:
        loss, tokens/sec, plus grad/param/update norms when ``health`` —
        the health variant changes the compiled program, so set this
        BEFORE init()/the first step).  Also installs ``recorder`` as
        the process-active one.  ``capture_cost`` harvests XLA
        cost/memory analysis from the compiled step (once per init(),
        cache-served lowering at the first batch's shapes) so step
        records carry ``perf/mfu`` / ``perf/hbm_bw_util`` /
        ``mem/peak_hbm_bytes``, plus live ``mem/device.*`` gauges
        (``capture_cost=False`` / ``BIGDL_PROFILE_CAPTURE=0`` disable
        both the capture and the polling)."""
        from ..observability.profile import (capture_enabled,
                                             install_device_memory_poller)
        self._recorder = recorder
        self._telemetry_health = bool(health)
        self._capture_cost = bool(capture_cost)
        if self._capture_cost and capture_enabled():
            install_device_memory_poller(recorder)
        if recorder.enabled:
            # goodput ledger over this trainer's whole mesh: end_step
            # folds h2d/compile/checkpoint.blocking/elastic.reshard
            # spans into badput, residual step time is goodput.  A
            # rebuilt trainer (elastic replan) reuses the recorder's
            # existing ledger — continuity across replans is the point
            # — but must adopt the NEW mesh size
            led = recorder.get_ledger()
            if led is None:
                from ..observability.goodput import GoodputLedger
                recorder.set_ledger(GoodputLedger(
                    name="train", devices=int(self.mesh.devices.size)))
            else:
                led.set_devices(int(self.mesh.devices.size))
        set_recorder(recorder)
        if (self._step_fn is not None
                and self._with_health != self._telemetry_active()):
            # re-jit with the new step signature WITHOUT losing training
            # progress: init() re-randomizes params, so stash and restore
            params, opt_state = self.params, self.opt_state
            self._step_fn = None
            self.init()
            if params is not None:
                self.params, self.opt_state = params, opt_state
        return self

    def set_trace_context(self, ctx, tracer=None):
        """Adopt a causal :class:`~bigdl_tpu.observability.context.
        TraceContext` (e.g. the elastic supervisor's run trace): each
        ``step()`` records a ``train.step`` span under it and every
        checkpoint save carries a child context to the async writer
        thread, so step → queue-wait → write shows up as ONE trace.
        ``ctx=None`` detaches.  ``tracer`` overrides the process
        default span store."""
        self._trace_ctx = ctx
        if tracer is not None:
            self._tracer = tracer
        return self

    def _trace_spine(self):
        from ..observability import tracing as trace_spine
        return self._tracer if self._tracer is not None \
            else trace_spine.get_tracer()

    def set_input_transform(self, fn):
        """Compile ``fn(tokens, rng) -> tokens`` into the jitted step —
        the device-side augmentation hook for this path (the host ships
        the raw wire format, e.g. uint8, and the transform runs inside
        the step's XLA program).  The rng is split off the step's
        traced key: recompile-safe, deterministic across resume.  Like
        ``set_telemetry(health=...)``, changing it after ``init()``
        re-jits without losing training progress."""
        self._input_transform = fn
        if self._step_fn is not None:
            params, opt_state = self.params, self.opt_state
            self._step_fn = None
            self.init()
            if params is not None:
                self.params, self.opt_state = params, opt_state
        return self

    def set_data_pipeline(self, dataset):
        """Attach a cursor-capable streaming dataset
        (``data.sharded.ShardedRecordDataSet``): every manifest
        checkpoint then records ``dataset.state()`` — the exact read
        position of the last consumed batch — and restore re-positions
        the stream, so a preempted run never re-sees or skips a sample.
        Feed ``fit(...)`` from ``dataset.stream()``."""
        self._data_pipeline = dataset
        return self

    def set_health(self, policy: str = "warn", flight_dir=None,
                   max_rollbacks: int = 2, stall_factor=None,
                   install_crash_hooks: bool = True, **monitor_kw):
        """Numeric-health sentinels over each step record (same layer as
        ``Optimizer.set_health``): NaN/Inf, loss-spike, grad-explosion
        detection riding the step's existing device→host results;
        ``policy="rollback"`` needs ``set_checkpoint`` and restores the
        newest intact checkpoint at most ``max_rollbacks`` times during
        ``fit()``.  ``flight_dir`` arms the crash flight recorder."""
        from ..observability.health import (FlightRecorder, HealthMonitor,
                                           StallWatchdog)
        if self._recorder is None:
            self.set_telemetry(Recorder())
        rec = self._recorder
        if flight_dir is not None:
            if self._flight is not None:     # reconfigure: one hook chain
                self._flight.uninstall()
            self._flight = FlightRecorder(rec, flight_dir)
            if install_crash_hooks:
                self._flight.install()
        self._health_monitor = HealthMonitor(
            policy=policy, recorder=rec, flight=self._flight, **monitor_kw)
        self._max_rollbacks = int(max_rollbacks)
        if stall_factor:
            if self._watchdog is not None:
                self._watchdog.stop()
            self._watchdog = StallWatchdog(rec,
                                           factor=float(stall_factor)).start()
        if self._http_server is not None:
            self._http_server.monitor = self._health_monitor
            self._http_server.watchdog = self._watchdog \
                or self._http_server.watchdog
        return self

    def serve_metrics(self, port: int = 0, host: str = "127.0.0.1",
                      watchdog: bool = True):
        """Live introspection server (``/metrics`` ``/healthz``
        ``/records``) for this trainer's recorder; see
        ``Optimizer.serve_metrics``.  Returns the server."""
        from ..observability.health import StallWatchdog
        from ..observability.http import IntrospectionServer
        if self._recorder is None:
            self.set_telemetry(Recorder())
        if watchdog and self._watchdog is None:
            self._watchdog = StallWatchdog(self._recorder).start()
        if self._http_server is not None:   # reconfigure: no leaked
            self._http_server.stop()        # thread/socket on the old port
        self._http_server = IntrospectionServer(
            self._recorder, port=port, host=host,
            watchdog=self._watchdog,
            monitor=self._health_monitor).start()
        return self._http_server

    def straggler_report(self):
        """Per-host step-time attribution — the "which worker drags the
        synchronous step" answer.  Each process's recorder ring only
        holds its OWN records, so under multi-host this does one
        on-demand ``process_allgather`` of the local mean step time
        (never on the step path) and attributes over the gathered
        fleet; single-host (or merged-ring) setups attribute over the
        local records and return None when there's nothing per-host."""
        from ..observability.health import attribute_stragglers
        recs = self._rec().recent_records(rec_type="step")
        if jax.process_count() > 1:
            durs = [r["dur"] for r in recs
                    if isinstance(r.get("dur"), (int, float))]
            if not durs:
                return None
            from jax.experimental import multihost_utils
            gathered = np.asarray(multihost_utils.process_allgather(
                jnp.asarray([float(np.mean(durs))]))).reshape(-1)
            return attribute_stragglers(
                [{"type": "step", "step": 0, "dur": float(m),
                  "scalars": {"host": h}}
                 for h, m in enumerate(gathered)])
        return attribute_stragglers(self._rec().recent_records())

    def _rec(self):
        return self._recorder if self._recorder is not None \
            else null_recorder()

    def _telemetry_active(self):
        """Compile health scalars into the step?  Only for an attached,
        ENABLED recorder — a disabled one must get the plain program."""
        return (self._recorder is not None and self._recorder.enabled
                and self._telemetry_health)

    def _capture_step_cost(self, tokens, targets, rng):
        """Harvest XLA cost/memory analysis for the compiled GSPMD step
        and attach the StepCostModel (per-step ``perf/mfu`` etc.).
        Lowers with the CONCRETE placed arrays — abstract avals would
        drop the shardings and analyze a different program; lowering
        never reads or donates the buffers, and the compile is
        cache-served against the dispatch about to happen.  Never
        raises."""
        from ..observability import profile as _profile
        rec = self._rec()
        if (not self._capture_cost or not rec.enabled
                or not _profile.capture_enabled()):
            return
        try:
            with rec.span("profile.capture"):
                cost = _profile.capture_compiled(
                    self._step_fn.lower(self.params, self.opt_state,
                                        tokens, targets, rng).compile())
        except Exception as e:
            cost = {"unavailable": ["capture_failed"], "error": repr(e)}
        _profile.attach_cost(rec, cost, kind="train_step")

    def account_collectives(self, tokens, targets):
        """Compile the current step for these shapes and parse the
        partitioned HLO for the collectives GSPMD actually inserted
        (the compiler owns the op choice on this path, so static
        estimates would lie).  Sets ``collective/*`` gauges on the
        recorder and returns ``{op: wire_bytes}`` + a total.  One extra
        trace+compile (cache-served if shapes match a prior step)."""
        if self._step_fn is None:
            self.init()
        sh = self._batch_sharding()
        tokens = jax.device_put(jnp.asarray(tokens), sh)
        targets = jax.device_put(jnp.asarray(targets), sh)
        rng = jax.random.PRNGKey(self.seed + 1)
        lowered = self._step_fn.lower(self.params, self.opt_state,
                                      tokens, targets, rng)
        hlo = lowered.compile().as_text()
        n = int(np.prod(list(self.mesh.shape.values())))
        ops = _acct.hlo_collective_ops(hlo, n)
        rec = self._rec()
        by_op = {}
        for op, _, wire in ops:
            by_op[op] = by_op.get(op, 0.0) + wire
        total = sum(by_op.values())
        rec.reset_gauges("collective/")
        rec.reset_gauges("comm/group.")
        for op, wire in by_op.items():
            rec.gauge(f"collective/{op.replace('-', '_')}_wire_bytes",
                      wire)
        rec.gauge("collective/wire_bytes_per_step", total)
        rec.gauge("collective/bytes_per_step", total)
        # per-axis-group attribution: map the replica groups the
        # partitioner emitted back onto mesh axes — on this path the
        # compiler owns the op choice, so the HLO is the only honest
        # source of "which axis paid these bytes" (the MoE ep
        # all-to-all, the fsdp gathers, the dp grad reduction each land
        # in their own comm/group.<axis>.* family)
        groups = _acct.hlo_group_breakdown(hlo, self.mesh)
        for label, d in groups.items():
            for op, wire in d.items():
                if op == "wire_bytes":
                    continue
                rec.gauge(f"comm/group.{label}."
                          f"{op.replace('-', '_')}_wire_bytes", wire)
            rec.gauge(f"comm/group.{label}.wire_bytes_per_step",
                      d["wire_bytes"])
        self._hlo_accounted = True
        return {"ops": by_op, "groups": groups,
                "wire_bytes_per_step": total}

    def step(self, tokens, targets):
        if self._step_fn is None:
            self.init()
        # jit traces lazily on first call: re-assert this trainer's ring
        # hooks so interleaved trainers on one model can't bake a foreign
        # mesh into our compiled step (compiled programs are unaffected)
        self.attach()
        rec = self._rec()
        step_span = None
        if self._trace_ctx is not None:
            step_span = self._trace_spine().begin(
                "train.step", self._trace_ctx, subsystem="train")
        rec.start_step(self._step_count)
        sh = self._batch_sharding()
        with rec.span("h2d"):
            tokens = jax.device_put(jnp.asarray(tokens), sh)
            targets = jax.device_put(jnp.asarray(targets), sh)
        rng = jax.random.fold_in(jax.random.PRNGKey(self.seed + 1),
                                 self._step_count)
        span_name = "train_step"
        if rec.enabled:
            sig = (tuple(tokens.shape), str(tokens.dtype),
                   tuple(targets.shape), str(targets.dtype))
            if sig not in self._seen_sigs:
                self._seen_sigs.add(sig)
                span_name = "train_step_compile"
                rec.scalar("recompile", 1.0)
                if self._cost_pending:
                    self._cost_pending = False
                    self._capture_step_cost(tokens, targets, rng)
        with rec.span(span_name):
            out = self._step_fn(self.params, self.opt_state, tokens,
                                targets, rng)
        if self._with_health:
            self.params, self.opt_state, loss, health = out
        else:
            self.params, self.opt_state, loss = out
            health = None
        self._step_count += 1
        if rec.enabled:
            wire = rec.gauge_value("collective/wire_bytes_per_step")
            if wire:
                rec.inc("collective/wire_bytes_total", wire)
            n_tok = int(np.prod(np.shape(tokens)))
            rec.inc("tokens_total", n_tok)
            rec.scalar("records", n_tok)   # records/sec == tokens/sec
            rec.scalar("loss", loss)
            if health:
                for k, v in health.items():
                    rec.scalar(k, v)
            if jax.process_count() > 1:
                # per-host step records: what the stall watchdog's
                # straggler attribution groups by
                rec.scalar("host", jax.process_index())
            record = rec.end_step(self._step_count - 1)
            if self._health_monitor is not None and record is not None:
                self._health_monitor.check_record(record)
        if step_span is not None:
            step_span.end(step=self._step_count - 1)
        return loss

    def evaluate(self, batches, steps: Optional[int] = None):
        """Token-weighted mean cross-entropy and perplexity over
        ``batches`` of (tokens, targets), computed with the same mesh
        placement as training (dropout off).  ≙ Evaluator/Loss validation
        for the flagship path."""
        import itertools
        if self.params is None:
            self.init()
        self.attach()
        model = self.model
        if getattr(self, "_eval_fn", None) is None:
            loss_chunk = self.loss_chunk

            def eval_fn(params, tokens, targets):
                # same chunked head+loss as training: evaluate must not
                # re-introduce the (B, S, V) logits memory wall
                return model.token_nll(params, tokens, targets,
                                       loss_chunk=loss_chunk,
                                       training=False)
            self._eval_fn = jax.jit(eval_fn)
        sh = self._batch_sharding()
        if steps is not None:   # islice: never pull an extra batch from a
            batches = itertools.islice(batches, steps)  # shared iterator
        sums, counts = [], []
        for tokens, targets in batches:
            tokens = jax.device_put(jnp.asarray(tokens, jnp.int32), sh)
            targets = jax.device_put(jnp.asarray(targets, jnp.int32), sh)
            s, c = self._eval_fn(self.params, tokens, targets)
            sums.append(s)      # device values: no per-batch host sync
            counts.append(c)
        total = float(sum(sums)) if sums else 0.0
        count = float(sum(counts)) if counts else 0.0
        if count == 0:
            raise ValueError(
                "evaluate: no valid tokens (empty batches, or every "
                "target is ignore_index)")
        loss = total / count
        res = {"loss": loss, "perplexity": float(np.exp(min(loss, 50.0))),
               "tokens": int(count)}
        vs = getattr(self, "_val_summary", None)
        if vs is not None:
            vs.add_scalar("Loss", res["loss"], self._step_count)
            vs.add_scalar("Perplexity", res["perplexity"],
                          self._step_count)
        return res

    # -- checkpointing --------------------------------------------------- #
    def _manifest_manager(self, path, keep=None, async_write=True):
        """CheckpointManager for this trainer with per-host shard
        ownership: shards are assigned round-robin over hosts by sorted
        shard name, each process snapshots and writes only the shards it
        owns, and host 0 merges the per-host part manifests into the
        single atomic MANIFEST.json commit (shared filesystem)."""
        from ..checkpoint import CheckpointManager
        mgr = self._ckpt_mgr
        if mgr is None or mgr.root != path:
            mgr = CheckpointManager(
                path, layout="manifest", async_write=async_write,
                keep_last=keep, recorder_fn=self._rec,
                process_index=jax.process_index(),
                process_count=jax.process_count())
            self._ckpt_mgr = mgr
        return mgr

    def _save_manifest_checkpoint(self, path: str, sync: bool = False,
                                  keep=None, async_write=True, tag=None):
        """Async sharded checkpoint via bigdl_tpu.checkpoint: params per
        top-level module + opt_state as CRC32C'd shards committed by an
        atomic manifest.  Only the blocking device→host copy of the
        OWNED shards runs on the step loop.

        The manifest records this trainer's mesh (v2), so restore can
        reshard onto a different one.  With ``shard_arrays`` each host
        writes per-device replica-0 slices (with index maps) instead of
        whole global trees — the representation that stays writable
        when no host can address a global array."""
        from ..checkpoint import reshard
        from ..checkpoint.manager import host_snapshot
        if self.params is None:
            raise ValueError("trainer not initialized; call init() first")
        mgr = self._manifest_manager(path, keep=keep,
                                     async_write=async_write)
        logical = {f"params/{mod}": sub
                   for mod, sub in self.params.items()}
        logical["opt_state"] = self.opt_state
        names = sorted(logical)
        shards, owned = {}, set()
        with self._rec().span("checkpoint.blocking"):
            for i, name in enumerate(names):
                tree = logical[name]
                if self._shard_arrays and reshard.all_array_leaves(tree):
                    # one slice shard per host per entry: every host
                    # enumerates every host's shard names (aligned file
                    # indices) but materializes only its own fragments
                    for k in range(mgr.process_count):
                        pname = f"{name}@p{k:03d}"
                        if k == mgr.process_index:
                            frag = reshard.split_fragments(
                                tree, process_index=k)
                            frag["of"] = name
                            shards[pname] = frag
                            owned.add(pname)
                        else:
                            shards[pname] = None
                elif i % mgr.process_count == mgr.process_index:
                    # whole-tree global shard, round-robin ownership
                    shards[name] = host_snapshot(tree)
                    owned.add(name)
                else:
                    # unowned placeholder: keeps shard indices aligned
                    # across hosts, never serialized
                    shards[name] = None
        meta = {"step": self._step_count, "seed": self.seed,
                "root": self.model.name}
        if self._data_pipeline is not None:
            # the data cursor is mesh-independent (the pipeline feeds
            # the GLOBAL batch), so it survives an elastic reshard
            # unchanged — dp4→dp2 resumes the identical sample stream
            meta["data_cursor"] = self._data_pipeline.state()
        mgr.save(shards, meta, tag=tag or f"step_{self._step_count}",
                 sync=sync, mesh=reshard.mesh_info(self.mesh),
                 owned=owned,
                 trace_ctx=self._trace_ctx.child()
                 if self._trace_ctx is not None else None)

    def save_checkpoint(self, path: str, layout: Optional[str] = None,
                        sync: bool = False, tag: Optional[str] = None):
        """Write params + optimizer state + step counter.

        ``layout="manifest"`` (or ``set_checkpoint(...,
        layout="manifest")``) uses the bigdl_tpu.checkpoint subsystem:
        async sharded writes, atomic manifest commit, CRC-verified
        resume.  The default ``"orbax"`` layout keeps the
        ecosystem-readable orbax directory: sharded jax Arrays are
        handed to orbax directly (``to_host=False``) so fsdp state is
        written shard-wise without materialising an unsharded host
        copy.  ≙ Optimizer.setCheckpoint for the compiler-partitioned
        flagship path."""
        import json
        import os
        from ..utils.serializer import save_pytree
        if layout is None:
            layout = self._ckpt_layout
        if layout == "manifest":
            return self._save_manifest_checkpoint(path, sync=sync, tag=tag)
        if self.params is None:
            raise ValueError("trainer not initialized; call init() first")
        # step-tagged snapshot + atomic 'latest' pointer (same crash-safe
        # pattern as Optimizer.save_checkpoint): a job killed mid-save
        # never destroys the previous snapshot.  An explicit tag (e.g.
        # the preemption path's preempt_step_<n>) names the dir, and
        # _prune_checkpoints' step_<n> pattern never collects it
        tag_dir = os.path.join(path, tag or f"step_{self._step_count}")
        save_pytree({"params": self.params, "opt_state": self.opt_state},
                    os.path.join(tag_dir, "state"), to_host=False)
        meta = {"step": self._step_count, "seed": self.seed,
                "root": self.model.name}
        if self._data_pipeline is not None:
            meta["data_cursor"] = self._data_pipeline.state()
        with open(os.path.join(tag_dir, "meta.json"), "w") as f:
            json.dump(meta, f)
        tmp = os.path.join(path, "latest.tmp")
        with open(tmp, "w") as f:
            f.write(os.path.basename(tag_dir))   # relocatable pointer
        os.replace(tmp, os.path.join(path, "latest"))

    def _rekey_root(self, tree, old_root, new_root):
        """Auto-named modules draw from a process-global uid counter, so a
        fresh trainer's param keys differ from the saved ones ONLY in the
        model-root prefix; rewrite it key-by-key (never by flatten
        order, which could silently permute same-shape leaves)."""
        if isinstance(tree, dict):
            out = {}
            for k, v in tree.items():
                if k == old_root:
                    k = new_root
                elif k.startswith(old_root + "."):
                    k = new_root + k[len(old_root):]
                out[k] = self._rekey_root(v, old_root, new_root)
            return out
        return tree

    def load_checkpoint(self, path: str):
        """Restore a save_checkpoint directory into this trainer: arrays
        come back on device with this trainer's shardings, and the step
        counter AND seed resume, so the data-order/dropout RNG stream
        continues exactly as in the uninterrupted run.  Manifest-layout
        checkpoints (CRC-verified, torn-checkpoint fallback) are tried
        first; the orbax layout remains readable."""
        import json
        import os
        from ..utils.serializer import load_pytree
        if self.params is None:
            self.init()
        restored = self._manifest_manager(path).restore_latest(
            with_manifest=True)
        if restored is not None and restored[0] == "manifest":
            _, trees, meta, mf = restored
            raw = {"params": {k[len("params/"):]: v
                              for k, v in trees.items()
                              if k.startswith("params/")},
                   "opt_state": trees["opt_state"]}
            return self._finish_restore(raw, meta, path,
                                        saved_mesh=mf.mesh if mf else None)
        latest = os.path.join(path, "latest")
        if os.path.exists(latest):
            with open(latest) as f:
                name = f.read().strip()
            if os.path.isabs(name) or os.sep in name:
                root = name     # legacy pointer holding a full path
            else:
                root = os.path.join(path, name)
        elif os.path.exists(os.path.join(path, "meta.json")):
            root = path     # direct snapshot directory
        else:
            raise FileNotFoundError(
                f"{path}: no 'latest' pointer or snapshot found")
        with open(os.path.join(root, "meta.json")) as f:
            meta = json.load(f)
        raw = load_pytree(os.path.join(root, "state"))
        return self._finish_restore(raw, meta, path)

    def _finish_restore(self, raw, meta, path, saved_mesh=None):
        """Validate a raw {params, opt_state} tree against this trainer
        and place it: shared tail of the manifest and orbax loaders.

        ``saved_mesh`` (the v2 manifest's save-time mesh) arms the
        reshard path: global arrays are mesh-invariant, so a topology
        change is purely a re-layout — ``device_put`` against THIS
        trainer's shardings — counted under ``elastic/*`` and recorded
        as an ``elastic_event``.  Shape mismatches raise errors that
        name both meshes and, when a mesh delta explains the mismatch,
        say so."""
        from ..checkpoint import reshard
        raw = self._rekey_root(raw, meta.get("root", self.model.name),
                               self.model.name)
        target_mesh = reshard.mesh_info(self.mesh)
        resharding = (saved_mesh is not None
                      and not reshard.same_mesh(saved_mesh, target_mesh))
        delta = reshard.describe_delta(saved_mesh, target_mesh)
        template = {"params": self.params, "opt_state": self.opt_state}
        if (jax.tree_util.tree_structure(raw)
                != jax.tree_util.tree_structure(template)):
            hint = f" (checkpoint {delta} — a mesh change never alters " \
                   "the tree structure; this is a different model)" \
                   if resharding else ""
            raise ValueError(
                f"{path}: checkpoint tree does not match this trainer's "
                f"model (after root-name normalisation){hint}")

        def dt(a):
            # dtype without materializing the leaf: np.asarray on a live
            # sharded template forces a device-to-host copy (and raises on
            # non-fully-addressable multi-host arrays)
            d = getattr(a, "dtype", None)
            return np.dtype(d) if d is not None else np.asarray(a).dtype

        def check(v, t, where):
            if tuple(np.shape(v)) != tuple(np.shape(t)) or dt(v) != dt(t):
                msg = (f"{path}: leaf {jax.tree_util.keystr(where)} is "
                       f"{np.shape(v)}/{dt(v)}, model expects "
                       f"{np.shape(t)}/{dt(t)}")
                why = reshard.explain_shape_delta(
                    np.shape(v), np.shape(t), saved_mesh, target_mesh)
                if why is not None:
                    msg += (f". Explainable by the mesh delta — {why}. "
                            f"Checkpoint {delta}. Re-save it with "
                            "shard_arrays=True (elastic v2 slice shards "
                            "carry global index maps) and restore will "
                            "reassemble and reshard onto this mesh; see "
                            "docs/checkpointing.md § Elastic resume.")
                elif saved_mesh is not None:
                    msg += (f". Checkpoint {delta}; global shapes are "
                            "mesh-invariant, so this mismatch is NOT "
                            "explained by the mesh change — the saved "
                            "model differs from this trainer's.")
                raise ValueError(msg)
            return v

        raw = jax.tree_util.tree_map_with_path(
            lambda w, v, t: check(v, t, w), raw, template)
        rec = self._rec()
        shardings = self._param_shardings(self.params)
        with rec.span("elastic.reshard" if resharding
                      else "checkpoint.restore"):
            # place-then-own: device_put shards the host leaf during the
            # transfer (no full-size unsharded device intermediate — the
            # property the orbax save path promises), and the sharded
            # jnp.array(copy=True) guarantees jax-owned buffers —
            # device_put of an aligned numpy array can be zero-copy on
            # CPU, and params are donated every step
            self.params = jax.tree_util.tree_map(
                lambda v, s: jnp.array(jax.device_put(np.asarray(v), s),
                                       copy=True),
                raw["params"], shardings)
            # opt-state leaves stay UNCOMMITTED: at init they come out of
            # jit the same way, and the next step call's jit dispatch
            # places them against the params' shardings without the
            # committed-device conflicts an explicit device_put would
            # cause — which is also what re-partitions Adam moments onto
            # a changed mesh without spelling their layout out twice.
            # copy=True, not asarray: a zero-copy alias of the loader's
            # numpy buffer must never reach the donating step (see
            # Optimizer.load_checkpoint)
            self.opt_state = jax.tree_util.tree_map(
                lambda v: jnp.array(np.asarray(v), copy=True),
                raw["opt_state"])
        if resharding:
            n_leaves = len(jax.tree_util.tree_leaves(raw))
            rec.inc("elastic/reshards")
            rec.inc("elastic/resharded_leaves", n_leaves)
            rec.emit_record("elastic_event", kind="reshard",
                            step=meta.get("step"), saved_mesh=saved_mesh,
                            target_mesh=target_mesh, leaves=n_leaves)
            print(f"[elastic] resharded {n_leaves} leaves: {delta}",
                  flush=True)
        self._step_count = meta["step"]
        self.seed = meta.get("seed", self.seed)
        cursor = meta.get("data_cursor")
        if cursor is not None and self._data_pipeline is not None:
            self._data_pipeline.restore(cursor)
        return self

    def set_checkpoint(self, path: str, every_steps: int = 1000,
                       keep: int = 3, layout: str = "orbax",
                       async_write: bool = True,
                       shard_arrays: bool = False,
                       handle_preemption: bool = False):
        """Checkpoint every ``every_steps`` steps during fit(), retaining
        the newest ``keep`` snapshots (0 = keep all)
        (≙ Optimizer.setCheckpoint with a several_iteration trigger).
        ``layout="manifest"`` routes through bigdl_tpu.checkpoint:
        background sharded writes with per-host shard ownership and an
        atomic CRC-verified manifest commit; retention then runs in the
        manager's GC.

        ``shard_arrays`` (manifest layout) switches to elastic v2 slice
        shards: each host writes per-device replica-0 array fragments
        with global index maps, so restore can reassemble on ANY mesh —
        the save mode that works even when no host addresses a global
        array.  ``handle_preemption`` installs a SIGTERM handler (same
        contract as ``Optimizer.set_checkpoint``): fit() finishes the
        in-flight write, commits a final ``preempt_step_<n>`` checkpoint
        synchronously, and returns cleanly."""
        if every_steps < 1:
            raise ValueError("every_steps must be >= 1")
        if keep < 0:
            raise ValueError("keep must be >= 0")
        if layout not in ("orbax", "manifest"):
            raise ValueError(f"unknown checkpoint layout {layout!r}")
        if shard_arrays and layout != "manifest":
            raise ValueError("shard_arrays requires layout='manifest'")
        self._ckpt = (path, int(every_steps), int(keep))
        self._ckpt_layout = layout
        self._shard_arrays = bool(shard_arrays)
        if layout == "manifest":
            self._ckpt_mgr = None       # rebuild with this retention
            self._manifest_manager(path, keep=int(keep) or None,
                                   async_write=async_write)
        if handle_preemption:
            from ..checkpoint import PreemptionHandler
            if self._preemption is None:
                self._preemption = PreemptionHandler()
            self._preemption.install()
        return self

    def _prune_checkpoints(self, path: str, keep: int):
        import os
        import re
        import shutil
        if keep < 1:
            return
        latest = os.path.join(path, "latest")
        pointed = None
        if os.path.exists(latest):
            with open(latest) as f:
                pointed = os.path.basename(f.read().strip())
        snaps = []
        for d in os.listdir(path):
            m = re.fullmatch(r"step_(\d+)", d)
            full = os.path.join(path, d)
            if m and os.path.isdir(full):
                # rank by mtime, not step number: a run resumed from an
                # older snapshot must not have its fresh checkpoints
                # crowded out by stale higher-step dirs of a dead run
                snaps.append((os.path.getmtime(full), int(m.group(1)),
                              d, full))
        snaps.sort()   # mtime first; step number breaks coarse-mtime ties
        for _, _, name, full in snaps[:-keep]:
            if name != pointed:  # never delete the snapshot 'latest' names
                shutil.rmtree(full, ignore_errors=True)

    def set_weight_stream(self, publisher):
        """Attach a live train→serve weight stream
        (:class:`~bigdl_tpu.serving.WeightStreamPublisher`): evaluated
        once per ``fit`` step against the global step count; on fire
        the sharded params are snapshotted to owning host copies and
        published through the canary gate off the step loop.  ``None``
        detaches."""
        self._weight_stream = publisher
        return self

    def set_val_summary(self, summary):
        """ValidationSummary target for :meth:`evaluate` results (≙
        Optimizer.set_val_summary): each evaluate() writes Loss and
        Perplexity at the current training step."""
        self._val_summary = summary
        return self

    def set_train_summary(self, summary):
        """TensorBoard Loss/Throughput scalars (≙
        Optimizer.set_train_summary, incl. set_summary_trigger gating).
        Losses are buffered as device values and flushed every
        ``summary_flush_every`` steps (default 100) and on exit — even
        on an exception — so summaries add no per-step device->host
        sync but a crashed run keeps its curve."""
        self._train_summary = summary
        return self

    def _flush_summary(self, buffered, tokens_seen, t0):
        """Write buffered (step, device_loss) pairs; returns []"""
        summary = self._train_summary
        trig = getattr(summary, "get_summary_trigger",
                       lambda _t: None)("Loss")
        for s, l in buffered:
            if trig is None or trig(SimpleNamespace(iteration=s)):
                summary.add_scalar("Loss", float(l), s)
        if buffered:
            wall = max(time.time() - t0, 1e-9)
            summary.add_scalar("Throughput", tokens_seen / wall,
                               buffered[-1][0])
        return []

    def fit(self, batches, steps: Optional[int] = None, log_every: int = 0,
            summary_flush_every: int = 100):
        losses = []
        buffered = []
        tokens_seen = 0
        ckpt = getattr(self, "_ckpt", None)
        summary = getattr(self, "_train_summary", None)
        t0 = time.time()
        if self._watchdog is not None:
            self._watchdog.start()      # re-arms after a previous fit()
        try:
            for i, (tokens, targets) in enumerate(batches):
                if steps is not None and i >= steps:
                    break
                try:
                    loss = self.step(tokens, targets)
                except DivergenceError as e:
                    mon = self._health_monitor
                    if (mon is None or mon.policy != "rollback"
                            or ckpt is None
                            or mon.rollbacks >= self._max_rollbacks):
                        raise
                    if self._ckpt_mgr is not None:
                        self._ckpt_mgr.wait()   # let an in-flight write
                        # commit: it may be the newest intact checkpoint
                    try:
                        self.load_checkpoint(ckpt[0])
                    except Exception:
                        raise e     # no restorable checkpoint: diverge
                    mon.rollbacks += 1
                    mon.reset_statistics()
                    mon.mark_recovered()
                    print(f"[health] rollback {mon.rollbacks}/"
                          f"{self._max_rollbacks}: {e}; resumed from "
                          f"step {self._step_count}", flush=True)
                    continue
                if log_every and (i + 1) % log_every == 0:
                    print(f"step {i + 1}: loss={float(loss):.4f} "
                          f"({(i + 1) / (time.time() - t0):.2f} it/s)")
                if (self._preemption is not None
                        and self._preemption.requested and ckpt):
                    # SIGTERM: finish any in-flight async write, commit
                    # a final checkpoint synchronously, stop cleanly —
                    # the elastic supervisor (or the next job) resumes
                    # it, on this mesh or a smaller one
                    losses.append(loss)
                    self.save_checkpoint(
                        ckpt[0], sync=True,
                        tag=f"preempt_step_{self._step_count}")
                    print(f"[preemption] final checkpoint at step "
                          f"{self._step_count} committed; stopping "
                          "cleanly", flush=True)
                    break
                if ckpt and self._step_count % ckpt[1] == 0:
                    self.save_checkpoint(ckpt[0])
                    if self._ckpt_layout == "orbax":
                        # manifest layout: retention runs in the
                        # manager's own GC on the writer thread
                        self._prune_checkpoints(ckpt[0], ckpt[2])
                stream = getattr(self, "_weight_stream", None)
                if stream is not None:
                    # owning host snapshot taken synchronously (the
                    # next step donates params); publish rides the
                    # stream worker.  loss stays on device — the shim
                    # state only carries the step count
                    stream.maybe_publish(self.params,
                                         step=self._step_count)
                losses.append(loss)
                if summary is not None:
                    tokens_seen += int(np.prod(np.shape(tokens)))
                    buffered.append((self._step_count, loss))
                    if len(buffered) >= summary_flush_every:
                        buffered = self._flush_summary(buffered,
                                                       tokens_seen, t0)
        finally:
            if summary is not None and buffered:
                self._flush_summary(buffered, tokens_seen, t0)
            if self._ckpt_mgr is not None:
                # drain the async writer: every triggered checkpoint is
                # committed and durable when fit() returns
                self._ckpt_mgr.wait()
            if self._watchdog is not None:
                # a finished loop is not a stalled one: /healthz scrapes
                # after fit() must not flag the growing idle step age
                self._watchdog.stop()
        return [float(l) for l in losses]
