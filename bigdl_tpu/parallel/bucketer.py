"""Bucketed, overlappable gradient exchange (≙ the reference's
``AllReduceParameter`` + ``FP16CompressedTensor`` pipeline,
arXiv:1804.05839).

One monolithic all-reduce of the whole gradient tree serializes the
exchange behind the *last* gradient of backward.  The reference BigDL
instead sliced gradients into per-partition blocks and overlapped their
exchange with compute; the XLA-native version of that trick is to emit
**one collective per fixed-size flat bucket** so the async collective
scheduler (`-start`/`-done` pairs on TPU) can launch each bucket's
all-reduce as soon as its inputs are ready — overlapping the exchange
with the tail of backward instead of waiting for all of it.

:class:`GradBucketer` packs gradient leaves into flat buckets of
``bucket_bytes`` in **backward-emission order** (reverse of the forward
flatten order — the deepest modules' gradients materialize first, so
their bucket's collective can start first), keeping each bucket
single-dtype so packing round-trips bit-exactly.  ``compress="fp16"``
halves the wire payload per bucket: pre-scale by 1/n in fp32, cast to
fp16 for the ring (the mean is what travels — a raw fp16 *sum* of n
shards can overflow half precision's 65504 range), upcast to the leaf
dtype after.  Uncompressed bucketed exchange is bit-identical to the
monolithic ``allreduce_gradients`` path (elementwise psum over the same
replicas; asserted in tests).
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..observability import collectives as _acct
from ._compat import axis_size

_CAST = {"fp16": jnp.float16, "float16": jnp.float16,
         "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16}


class GradBucketer:
    """Host-side bucket plan for one gradient-tree template.

    ``order`` controls packing order: ``"backward"`` (default — reverse
    flatten order, first-ready-first), ``"forward"``, or ``"size"``
    (largest leaves first, evening out bucket fill).  A leaf larger
    than ``bucket_bytes`` gets a bucket of its own.
    """

    def __init__(self, params_template, bucket_bytes: int = 4 << 20,
                 order: str = "backward"):
        if order not in ("backward", "forward", "size"):
            raise ValueError(f"unknown bucket order {order!r}")
        leaves, self.treedef = jax.tree_util.tree_flatten(params_template)
        self.n_leaves = len(leaves)
        self.shapes = [tuple(l.shape) for l in leaves]
        self.sizes = [int(np.prod(s)) if s else 1 for s in self.shapes]
        self.dtypes = [jnp.dtype(l.dtype) for l in leaves]
        self.bucket_bytes = int(bucket_bytes)
        idx = list(range(self.n_leaves))
        if order == "backward":
            idx = idx[::-1]
        elif order == "size":
            idx.sort(key=lambda i: -self.sizes[i])
        self.buckets: List[List[int]] = []      # lists of leaf indices
        cur, cur_bytes, cur_dt = [], 0, None
        for i in idx:
            nbytes = self.sizes[i] * self.dtypes[i].itemsize
            if cur and (self.dtypes[i] != cur_dt
                        or cur_bytes + nbytes > self.bucket_bytes):
                self.buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += nbytes
            cur_dt = self.dtypes[i]
        if cur:
            self.buckets.append(cur)

    def __len__(self):
        return len(self.buckets)

    # -- pack / unpack ---------------------------------------------------- #
    def pack(self, grads):
        """Gradient tree -> list of flat single-dtype bucket vectors."""
        leaves = jax.tree_util.tree_leaves(grads)
        out = []
        for bucket in self.buckets:
            if len(bucket) == 1:
                out.append(jnp.ravel(leaves[bucket[0]]))
            else:
                out.append(jnp.concatenate(
                    [jnp.ravel(leaves[i]) for i in bucket]))
        return out

    def unpack(self, vecs):
        """Inverse of :meth:`pack`."""
        leaves = [None] * self.n_leaves
        for bucket, vec in zip(self.buckets, vecs):
            off = 0
            for i in bucket:
                leaves[i] = vec[off:off + self.sizes[i]].reshape(
                    self.shapes[i]).astype(self.dtypes[i])
                off += self.sizes[i]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    # -- the exchange ------------------------------------------------------ #
    def allreduce(self, grads, axis_name: str = "dp",
                  compress: Optional[str] = None, mean: bool = True,
                  group: Optional[str] = None):
        """Per-bucket all-reduce of ``grads`` inside ``shard_map``.

        Trace-time accounting mirrors ``allreduce_gradients``:
        ``collective/allreduce_bytes`` raw vs ``_wire_bytes`` post-
        compression, plus a ``collective/buckets`` gauge with the
        per-step collective count.  ``group`` (default: the axis name)
        attributes the volume to its parallelism group's
        ``comm/group.<axis>.*`` family — on a composed mesh each axis
        runs its own bucket stream, accounted separately."""
        n = axis_size(axis_name)
        if group is None and isinstance(axis_name, str):
            group = axis_name
        cast_to = _CAST.get(compress)
        vecs = self.pack(grads)
        raw = sum(_acct.leaf_bytes(v) for v in vecs)
        wire_item = _acct.compressed_itemsize(compress)
        wire = raw if wire_item is None else sum(
            v.shape[0] * wire_item for v in vecs)
        _acct.account_collective("allreduce",
                                 _acct.ring_allreduce_bytes(raw, n),
                                 _acct.ring_allreduce_bytes(wire, n),
                                 group=group)
        from ..observability.recorder import get_recorder
        rec = get_recorder()
        if rec.enabled:
            # accumulated, like bytes_per_step: a composed/overlap-
            # chunked step issues several bucket streams per trace, and
            # last-write would under-report all but the final stream.
            # The collective/ and comm/group. prefixes reset together
            # on every rebuild AND re-trace, so single-stream paths
            # read exactly as before
            rec.gauge("collective/buckets",
                      rec.gauge_value("collective/buckets")
                      + float(len(vecs)))
            if group is not None:
                rec.gauge(f"comm/group.{group}.buckets",
                          rec.gauge_value(f"comm/group.{group}.buckets")
                          + float(len(vecs)))

        out = []
        for v in vecs:
            orig = v.dtype
            if cast_to is not None:
                if mean:        # the 1/n mean travels: fp16-sum-safe
                    v = (v.astype(jnp.float32) / n).astype(cast_to)
                else:
                    v = v.astype(cast_to)
                v = lax.psum(v, axis_name).astype(orig)
            else:
                v = lax.pmean(v, axis_name) if mean \
                    else lax.psum(v, axis_name)
            out.append(v)
        return self.unpack(out)
