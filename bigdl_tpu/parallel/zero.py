"""ZeRO-1 sharded weight update for the data-parallel path
(arXiv:2004.13336, "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training").

Plain dp keeps params AND optimizer state replicated: every replica
all-reduces the full gradient and applies the identical full update —
N copies of the Adam moments in HBM and an all-reduce (2·S·(n−1)/n wire
bytes) where a reduce-scatter + all-gather (same total) would let each
replica touch 1/N of the update math and 1/N of the optimizer state.

:class:`Zero1Layout` is the host-side plan that makes the dp step do
exactly that:

  * leaves whose dim 0 divides the axis size (``shardable_mask_dim0``)
    are exchanged with a per-leaf ``psum_scatter`` and updated as dim-0
    shards — natural per-tensor "buckets" XLA's async scheduler can
    overlap with the tail of backward;
  * every other leaf (biases, scalars, odd shapes) is raveled into one
    or more **padded flat buckets** (zero-padded to a multiple of the
    axis size, optionally split at ``bucket_bytes``), scattered the same
    way — nothing falls back to a dense all-reduce, so optimizer-state
    memory is exactly 1/N for the whole tree;
  * updated shards ride ``all_gather`` back to full replicated params
    for the next forward.

The shard representation ("shard space") is the pytree
``{"leaves": [dim0-shard, ...], "flat": [chunk, ...]}``.  Optimizer
state initialized over the *global* shard space (full leaves + padded
flat vectors) mirrors this structure, so ``P("dp")`` in/out specs hand
each replica exactly its 1/N moment shard inside ``shard_map`` — the
memory claim is enforced by sharding metadata, not convention.

Elementwise optimizers (SGD/Adam/AdamW/Adagrad/RMSprop/Adadelta/
Adamax/Ftrl) are exact under this re-partitioning; per-TENSOR-norm
methods (LARS/LAMB) are not (a shard's norm is not the tensor's norm)
and are rejected by DistriOptimizer at configuration time.
"""
from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..observability import collectives as _acct
from .allreduce import _path_str, shardable_mask_dim0

log = logging.getLogger(__name__)


class Zero1Layout:
    """Shard-space plan for one params template over a dp axis of size n.

    The plan is pure host-side metadata (leaf order, flat-bucket
    membership, pad sizes) computed from GLOBAL shapes; all array
    methods are trace-safe and meant to run inside ``shard_map``.
    """

    def __init__(self, params_template, n: int,
                 bucket_bytes: Optional[int] = None):
        self.n = int(n)
        flat, self.treedef = jax.tree_util.tree_flatten(params_template)
        with_path, _ = jax.tree_util.tree_flatten_with_path(params_template)
        mask = jax.tree_util.tree_leaves(shardable_mask_dim0(
            params_template, self.n))
        self.n_leaves = len(flat)
        self.sharded_idx = [i for i, m in enumerate(mask) if m]
        flat_leaf_idx = [i for i, m in enumerate(mask) if not m]
        self.flat_names = [_path_str(with_path[i][0]) for i in flat_leaf_idx]

        self.shapes = [tuple(l.shape) for l in flat]
        self.dtypes = [jnp.dtype(l.dtype) for l in flat]

        # flat buckets: group the non-dim0-shardable leaves (by dtype, so
        # a bucket round-trips exactly), split at bucket_bytes, pad each
        # bucket to a multiple of n
        groups = {}
        for i in flat_leaf_idx:
            groups.setdefault(jnp.dtype(flat[i].dtype), []).append(i)
        self.buckets = []       # (dtype, [leaf idx], [sizes], pad)
        for dt, idxs in groups.items():
            cur, cur_bytes = [], 0
            for i in idxs:
                sz = int(np.prod(self.shapes[i])) if self.shapes[i] else 1
                nbytes = sz * dt.itemsize
                if cur and bucket_bytes and cur_bytes + nbytes > bucket_bytes:
                    self._close_bucket(dt, cur)
                    cur, cur_bytes = [], 0
                cur.append(i)
                cur_bytes += nbytes
            if cur:
                self._close_bucket(dt, cur)

    def _close_bucket(self, dt, idxs):
        sizes = [int(np.prod(self.shapes[i])) if self.shapes[i] else 1
                 for i in idxs]
        pad = (-sum(sizes)) % self.n
        self.buckets.append((dt, list(idxs), sizes, pad))

    def _bucket_meta(self, bi):
        return self.buckets[bi]

    # -- shard-space construction --------------------------------------- #
    def _pack_bucket(self, leaves, bi):
        dt, idxs, sizes, pad = self._bucket_meta(bi)
        vec = jnp.concatenate([jnp.ravel(leaves[i]) for i in idxs]) \
            if len(idxs) > 1 else jnp.ravel(leaves[idxs[0]])
        if pad:
            vec = jnp.pad(vec, (0, pad))
        return vec

    def global_shard_space(self, tree):
        """Full-size shard-space view of ``tree``: dim0-shardable leaves
        as-is, the rest packed into padded flat buckets.  Optimizer
        state is initialized over THIS tree; sharded with ``P('dp')``
        specs it lives 1/N per replica."""
        leaves = jax.tree_util.tree_leaves(tree)
        return {"leaves": [leaves[i] for i in self.sharded_idx],
                "flat": [self._pack_bucket(leaves, bi)
                         for bi in range(len(self.buckets))]}

    def spec_tree(self, axes=("dp",)):
        """PartitionSpecs of the global shard space: every entry is a
        dim-0 shard over ``axes``.  On a composed mesh the shard space
        of a pipeline stage's params is stacked over ``pp`` *and*
        scattered over ``dp`` — ``axes=("pp", "dp")`` composes the two
        on dim 0 (pp-major, matching shard_map's split order)."""
        axes = tuple(axes)
        spec = P(axes if len(axes) > 1 else axes[0])
        return {"leaves": [spec] * len(self.sharded_idx),
                "flat": [spec] * len(self.buckets)}

    def stacked_space_zeros(self, n_stack: int = 1):
        """Zero-filled GLOBAL shard space, stage-stacked on dim 0.

        For a pp×dp composition the outside-jit storage of the shard
        space stacks every pipeline stage's (per-stage) shard space on
        dim 0 — ``n_stack`` = number of stages; sharded
        ``P(("pp", "dp"))`` each device holds exactly its stage's 1/dp
        slice.  Optimizer state initialized over this tree is correct
        for every value-independent OptimMethod init (zeros/constant
        moments — all of ours)."""
        leaves = []
        for i in self.sharded_idx:
            sh = self.shapes[i]
            leaves.append(jnp.zeros((n_stack * sh[0],) + tuple(sh[1:]),
                                    self.dtypes[i]))
        flat = []
        for bi in range(len(self.buckets)):
            dt, _, sizes, pad = self._bucket_meta(bi)
            flat.append(jnp.zeros((n_stack * (sum(sizes) + pad),), dt))
        return {"leaves": leaves, "flat": flat}

    def local_shard(self, tree, idx, axis_name="dp"):
        """This replica's 1/N slice of a replicated full tree (used for
        params: they arrive replicated, the update only needs the local
        rows).  ``idx = lax.axis_index(axis)``."""
        del axis_name
        leaves = jax.tree_util.tree_leaves(tree)
        out_l = []
        for i in self.sharded_idx:
            rows = self.shapes[i][0] // self.n
            out_l.append(lax.dynamic_slice_in_dim(leaves[i], idx * rows,
                                                  rows, axis=0))
        out_f = []
        for bi in range(len(self.buckets)):
            vec = self._pack_bucket(leaves, bi)
            chunk = vec.shape[0] // self.n
            out_f.append(lax.dynamic_slice_in_dim(vec, idx * chunk, chunk,
                                                  axis=0))
        return {"leaves": out_l, "flat": out_f}

    # -- collectives ------------------------------------------------------ #
    def scatter_grads(self, grads, axis_name="dp", compress=None,
                      mean=True, group=None):
        """Full (per-replica) grads -> this replica's shard-space slice of
        the reduced grads, via per-leaf/per-bucket ``psum_scatter``
        (S·(n−1)/n wire bytes vs the all-reduce's 2·S·(n−1)/n).

        ``compress="fp16"|"bf16"`` halves the wire payload: grads are
        pre-scaled by 1/n in fp32 (mean on the wire — bounds the ring
        accumulation and cannot overflow fp16's range the way a raw sum
        can), cast down, summed, and upcast after.  Accounting lands in
        the ``collective/reduce_scatter*`` gauges pre/post compression.
        """
        n = self.n
        if group is None and isinstance(axis_name, str):
            group = axis_name
        leaves = jax.tree_util.tree_leaves(grads)
        wire_item = _acct.compressed_itemsize(compress)
        cast_to = {"fp16": jnp.float16, "float16": jnp.float16,
                   "bf16": jnp.bfloat16,
                   "bfloat16": jnp.bfloat16}.get(compress)
        raw = [0]

        def rs(x):
            raw[0] += _acct.leaf_bytes(x)
            orig = x.dtype
            if cast_to is not None:
                if mean:
                    x = (x.astype(jnp.float32) / n).astype(cast_to)
                else:
                    x = x.astype(cast_to)
            out = lax.psum_scatter(x, axis_name, scatter_dimension=0,
                                   tiled=True)
            out = out.astype(orig)
            if mean and cast_to is None:
                out = out / n
            return out

        out_l = [rs(leaves[i]) for i in self.sharded_idx]
        out_f = [rs(self._pack_bucket(leaves, bi))
                 for bi in range(len(self.buckets))]
        wire = raw[0] if wire_item is None else sum(
            (_acct.leaf_bytes(leaves[i], wire_item)
             for i in self.sharded_idx), 0) + sum(
            (self._bucket_meta(bi)[3] + sum(self._bucket_meta(bi)[2]))
            * wire_item for bi in range(len(self.buckets)))
        _acct.account_collective("reduce_scatter",
                                 _acct.ring_gather_bytes(raw[0], n),
                                 _acct.ring_gather_bytes(wire, n),
                                 group=group)
        return {"leaves": out_l, "flat": out_f}

    def gather_params(self, shard_space, axis_name="dp", group=None):
        """Updated shard-space params -> full replicated tree via
        per-leaf/per-bucket ``all_gather`` (the getWeights fetch)."""
        n = self.n
        if group is None and isinstance(axis_name, str):
            group = axis_name
        raw = [0]

        def ag(x):
            out = lax.all_gather(x, axis_name, axis=0, tiled=True)
            raw[0] += _acct.leaf_bytes(out)
            return out

        full = [None] * self.n_leaves
        for k, i in enumerate(self.sharded_idx):
            full[i] = ag(shard_space["leaves"][k])
        for bi in range(len(self.buckets)):
            dt, idxs, sizes, pad = self._bucket_meta(bi)
            vec = ag(shard_space["flat"][bi])
            if pad:
                vec = vec[:vec.shape[0] - pad]
            off = 0
            for i, sz in zip(idxs, sizes):
                full[i] = vec[off:off + sz].reshape(self.shapes[i])
                off += sz
        _acct.account_collective("allgather",
                                 _acct.ring_gather_bytes(raw[0], n),
                                 _acct.ring_gather_bytes(raw[0], n),
                                 group=group)
        return jax.tree_util.tree_unflatten(self.treedef, full)

    # -- bookkeeping ------------------------------------------------------ #
    def opt_state_bytes_per_replica(self, opt_state) -> int:
        """Host-side: this replica's share of the moment bytes (scalars
        like the step counter stay replicated and are counted whole)."""
        total = 0
        for leaf in jax.tree_util.tree_leaves(opt_state):
            b = _acct.leaf_bytes(leaf)
            total += b // self.n if getattr(leaf, "ndim", 0) > 0 else b
        return total

    def describe(self) -> str:
        nb = len(self.buckets)
        return (f"zero1: {len(self.sharded_idx)} dim0-sharded leaves, "
                f"{len(self.flat_names)} flat-bucketed leaves in {nb} "
                f"bucket{'s' if nb != 1 else ''} over n={self.n}")


class Zero1Optim:
    """OptimMethod adapter: initializes the inner method's state over the
    GLOBAL shard space (so ``P('dp')`` specs shard the moments 1/N) and
    delegates updates, which the zero1 step calls with shard-space
    trees.  ``inner`` may already be clipping-wrapped."""

    def __init__(self, inner, layout: Zero1Layout):
        self.inner = inner
        self.layout = layout

    def init_state(self, params):
        return self.inner.init_state(self.layout.global_shard_space(params))

    def update(self, grads, params, state):
        return self.inner.update(grads, params, state)

    def get_learning_rate(self, state):
        return self.inner.get_learning_rate(state)
