"""One declarative template for composed dp×fsdp×tp×pp(+ep) training.

The MULTICHIP dryruns prove every parallelism axis individually; this
module is the production entry point that composes them: a single
``ComposedConfig`` names the mesh template (``"dp2,tp2,pp2"``) and the
roofline knobs (zero1 sharded update, bucketed/compressed dp-group
collectives, fused optimizer kernels, bubble-overlapped gradient
chunks), and :func:`build_trainer` picks the right engine:

  * a ``pp`` axis > 1 -> :class:`~bigdl_tpu.parallel.pipeline.
    PipelineLMTrainer` (manual GPipe schedule; dp manual, tp/sp auto) —
    the path where zero1/bucketing/overlap are explicit collectives;
  * otherwise -> :class:`~bigdl_tpu.parallel.spmd.SpmdTrainer` (GSPMD:
    dp/fsdp/tp/sp/ep all auto) — zero1 rides sharding annotations
    (arXiv:2004.13336) and the compiler owns the collectives, so the
    manual bucket/compress knobs are rejected rather than ignored.

Which win applies on which axis group, and what the parity taxonomy
says about each, is documented in docs/distributed.md § Composed
parallelism.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Union

from . import mesh as mesh_lib


@dataclass
class ComposedConfig:
    """Declarative composed-parallelism configuration.

    ``template`` is the full-capacity mesh ({axis: size} or a template
    string) — also what :func:`bigdl_tpu.elastic.plan_mesh` replans
    from when capacity changes.
    """
    template: Union[str, Dict[str, int]]
    zero1: bool = False
    bucket_bytes: Optional[int] = None
    compress: Optional[str] = None
    fused_optim: bool = False
    overlap_grad_chunks: int = 1
    n_microbatches: int = 4
    loss_chunk: Optional[int] = None
    grad_accum: int = 1
    min_fsdp_size: int = 2 ** 16
    zero1_min_size: Optional[int] = None
    clip_norm: Optional[float] = None
    seed: int = 0
    extra: Dict[str, Any] = field(default_factory=dict)

    def axes(self) -> Dict[str, int]:
        return mesh_lib.parse_template(self.template)


def build_trainer(model, optim, config: ComposedConfig, devices=None):
    """Build the (un-``init()``-ed) trainer for a composed config.

    Raises on knob/engine combinations that would silently degrade:
    the GSPMD path has no manual dp bucket stream (the partitioner
    owns the collectives), and the pipeline path has no fsdp layering
    (stage params are stacked, ZeRO-3-by-sharding doesn't apply).
    """
    axes = config.axes()
    mesh = mesh_lib.create_mesh(axes, devices)
    if axes.get("pp", 1) > 1:
        from .pipeline import PipelineLMTrainer
        if "fsdp" in axes and axes["fsdp"] > 1:
            raise ValueError(
                "fsdp does not compose with the pipeline engine (stage "
                "params are layer-stacked; use zero1 for the sharded "
                "update, or drop pp and let SpmdTrainer layer fsdp)")
        if config.grad_accum > 1:
            raise ValueError(
                "grad_accum is the GSPMD engine's microbatching; the "
                "pipeline engine accumulates via n_microbatches (and "
                "overlap_grad_chunks) — silently dropping it would "
                "shrink the effective batch")
        return PipelineLMTrainer(
            model, optim, mesh,
            n_microbatches=config.n_microbatches,
            seed=config.seed, loss_chunk=config.loss_chunk,
            zero1=config.zero1, bucket_bytes=config.bucket_bytes,
            compress=config.compress, fused_optim=config.fused_optim,
            overlap_grad_chunks=config.overlap_grad_chunks,
            clip_norm=config.clip_norm, **config.extra)
    from .spmd import SpmdTrainer
    for knob in ("bucket_bytes", "compress", "fused_optim",
                 "clip_norm"):
        if getattr(config, knob):
            raise ValueError(
                f"{knob} is a manual-collective/update knob: the GSPMD "
                "engine's collectives and update are compiler-owned "
                "(set pp>1 for the manual pipeline engine, or drop the "
                "knob)")
    if config.overlap_grad_chunks > 1:
        raise ValueError(
            "overlap_grad_chunks schedules the GPipe bubble; it needs "
            "a pp axis > 1")
    if config.n_microbatches != ComposedConfig.n_microbatches:
        raise ValueError(
            "n_microbatches is the pipeline engine's schedule knob; "
            "the GSPMD engine microbatches via grad_accum — silently "
            "dropping it would change the schedule you asked for")
    return SpmdTrainer(
        model, optim, mesh=mesh,
        fsdp=axes.get("fsdp", 1) > 1,
        seed=config.seed, min_fsdp_size=config.min_fsdp_size,
        grad_accum=config.grad_accum, loss_chunk=config.loss_chunk,
        zero1=config.zero1, zero1_min_size=config.zero1_min_size,
        **config.extra)
