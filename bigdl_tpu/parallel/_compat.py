"""Version-compat shim: `jax.shard_map` (new, check_vma) vs
`jax.experimental.shard_map` (old, check_rep), plus `lax.axis_size`
(absent before jax 0.5). One copy, imported by every explicit-SPMD
module."""
from __future__ import annotations

from jax import lax as _lax


def axis_size(axis_name) -> int:
    """Static size of a mapped mesh axis, callable at trace time inside
    shard_map/pmap.  `lax.axis_size` where available; on older jax,
    `lax.psum(1, axis)` — special-cased to return a concrete int."""
    fn = getattr(_lax, "axis_size", None)
    if fn is not None:
        return int(fn(axis_name))
    return int(_lax.psum(1, axis_name))

try:
    from jax import shard_map as _shard_map_fn

    def shard_map(f, mesh, in_specs, out_specs, check_rep: bool = False,
                  manual_axes=None):
        """`manual_axes`: mesh axes handled manually; the rest stay AUTO
        (GSPMD-partitioned) — how pp composes with tp in the pipeline
        trainer. None = all axes manual (classic shard_map)."""
        kw = {}
        if manual_axes is not None:
            kw["axis_names"] = frozenset(manual_axes)
        return _shard_map_fn(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_rep,
                             **kw)
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map_fn

    def shard_map(f, mesh, in_specs, out_specs, check_rep: bool = False,
                  manual_axes=None):
        kw = {}
        if manual_axes is not None:
            # old API spells it inside-out: list the AUTO axes instead
            kw["auto"] = (frozenset(mesh.axis_names)
                          - frozenset(manual_axes))
        return _shard_map_fn(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=check_rep,
                             **kw)
