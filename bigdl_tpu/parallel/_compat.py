"""Version-compat shim: `jax.shard_map` (new, check_vma) vs
`jax.experimental.shard_map` (old, check_rep). One copy, imported by every
explicit-SPMD module."""
from __future__ import annotations

try:
    from jax import shard_map as _shard_map_fn

    def shard_map(f, mesh, in_specs, out_specs, check_rep: bool = False):
        return _shard_map_fn(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_rep)
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map_fn

    def shard_map(f, mesh, in_specs, out_specs, check_rep: bool = False):
        return _shard_map_fn(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=check_rep)
