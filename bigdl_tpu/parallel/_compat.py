"""Version-compat shim: `jax.shard_map` (new, check_vma) vs
`jax.experimental.shard_map` (old, check_rep). One copy, imported by every
explicit-SPMD module."""
from __future__ import annotations

try:
    from jax import shard_map as _shard_map_fn

    def shard_map(f, mesh, in_specs, out_specs, check_rep: bool = False,
                  manual_axes=None):
        """`manual_axes`: mesh axes handled manually; the rest stay AUTO
        (GSPMD-partitioned) — how pp composes with tp in the pipeline
        trainer. None = all axes manual (classic shard_map)."""
        kw = {}
        if manual_axes is not None:
            kw["axis_names"] = frozenset(manual_axes)
        return _shard_map_fn(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_rep,
                             **kw)
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map_fn

    def shard_map(f, mesh, in_specs, out_specs, check_rep: bool = False,
                  manual_axes=None):
        if manual_axes is not None:
            raise NotImplementedError(
                "partial-manual shard_map (auto axes) needs jax>=0.6 "
                "jax.shard_map(axis_names=...)")
        return _shard_map_fn(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=check_rep)
