"""Pipeline parallelism over the ``pp`` mesh axis.

GSPMD does not partition by *layer*; pipelining is inherently a manual
schedule, so this is a shard_map program: each pp rank holds one stage's
parameters (stacked layer params sharded on their leading axis), and a
``lax.scan`` runs the GPipe schedule — microbatches enter stage 0, flow
stage-to-stage via ``lax.ppermute`` (one ICI hop per tick), and leave from
the last stage.  With M microbatches and S stages the scan runs M + S - 1
ticks; every tick all stages compute concurrently (the bubble is the usual
(S-1)/(M+S-1)).

AD: ppermute transposes to the reverse rotation and the scan transposes to
the reverse schedule, so ``jax.grad`` through :func:`pipeline_run` is the
standard 1F1B-equivalent backward pipeline — no hand-written backward.

The reference has nothing comparable (Spark tasks parallelise over *data*
only); this is part of going beyond its scale (SURVEY §2 #30).
"""
from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ._compat import axis_size, shard_map as _shard_map


def pipeline_run(stage_fn: Callable, stage_params, microbatches,
                 axis_name: str = "pp"):
    """Run the GPipe schedule. Call inside shard_map.

    stage_fn: (params_of_my_stage, x) -> y   (x, y same shape)
    stage_params: this rank's stage parameters (device-varying pytree)
    microbatches: (M, mb, ...) — the full microbatched input, replicated;
                  only stage 0 reads it.
    Returns (M, mb, ...) outputs, valid on the *last* stage (zeros
    elsewhere); weight per-stage reductions with :func:`last_stage_mask`.
    """
    n_stages = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    # shift-down (no wraparound): stage i -> i+1; stage 0 receives zeros
    perm = [(i, i + 1) for i in range(n_stages - 1)]
    is_first = (idx == 0)
    is_last = (idx == n_stages - 1)

    def tick(carry, t):
        state, outputs = carry
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        feed = lax.dynamic_index_in_dim(microbatches, mb_idx, 0,
                                        keepdims=False)
        x = jnp.where(is_first, feed, state)
        y = stage_fn(stage_params, x)
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        write = is_last & (t >= n_stages - 1)
        cur = lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, y, cur), out_idx, 0)
        state = lax.ppermute(y, axis_name, perm)
        return (state, outputs), None

    init = (jnp.zeros(mb_shape, microbatches.dtype),
            jnp.zeros((n_micro,) + mb_shape, microbatches.dtype))
    (_, outputs), _ = lax.scan(tick, init, jnp.arange(n_micro + n_stages - 1))
    return outputs


def last_stage_mask(axis_name: str = "pp"):
    """1.0 on the last pp rank, 0.0 elsewhere — multiply the loss by this
    and psum over pp so earlier stages contribute zero."""
    idx = lax.axis_index(axis_name)
    n = axis_size(axis_name)
    return (idx == n - 1).astype(jnp.float32)


def pipelined(stage_fn: Callable, mesh: Mesh, n_microbatches: int,
              axis_name: str = "pp"):
    """Wrap a stage function into a global-array pipelined forward.

    Returns ``f(stacked_params, x)`` where stacked_params leaves have a
    leading n_stages axis (sharded over pp) and x is (batch, ...);
    the result is the full-model output (batch, ...), replicated.
    """
    def global_fn(stacked_params, x):
        def local(params_stack, xs):
            # my slice of the stacked layer params: leading dim 1 -> squeeze
            my = jax.tree_util.tree_map(lambda p: p[0], params_stack)
            mbs = xs.reshape((n_microbatches, -1) + xs.shape[1:])
            outs = pipeline_run(stage_fn, my, mbs, axis_name)
            outs = outs.reshape(xs.shape)
            # broadcast the last stage's result to every rank
            outs = lax.psum(outs * last_stage_mask(axis_name), axis_name)
            return outs

        in_specs = (jax.tree_util.tree_map(lambda _: P(axis_name),
                                           stacked_params), P())
        return _shard_map(local, mesh, in_specs, P())(stacked_params, x)

    return global_fn


# --------------------------------------------------------------------- #
# transformer pipeline trainer                                          #
# --------------------------------------------------------------------- #
class PipelineLMTrainer:
    """GPipe training for TransformerLM over a 'pp' mesh axis (x optional
    'dp', 'tp', 'sp'): each pp rank owns n_layers/n_stages blocks (params
    stacked on a leading layer axis, sharded over pp); microbatches flow
    through pipeline_run's ppermute schedule; embedding feeds stage 0 and
    the LM head + loss run on the last stage (loss is masked+psum'd, so
    AD routes every gradient to the stage that owns it).  tp and sp are
    AUTO (GSPMD) axes inside the manual pp/dp shard_map: tensor parallel
    via the megatron pspecs, sequence parallel by sharding the sequence
    dim of the token batch.

    The optimizer update happens on the global (sharded) arrays outside
    the shard_map — GSPMD keeps the pp layout for block params/moments.
    """

    def __init__(self, model, optim, mesh, n_microbatches=4, seed=0,
                 loss_chunk=None):
        if model.frozen_param_names():
            raise NotImplementedError(
                "Module.freeze is not supported by PipelineLMTrainer "
                "(block params are stacked per stage, losing per-module "
                "identity); unfreeze or use SpmdTrainer")
        cfg = model.cfg
        if cfg.dropout:
            raise ValueError("PipelineLMTrainer requires dropout=0.0")
        if "pp" not in mesh.axis_names:
            raise ValueError("mesh needs a 'pp' axis")
        self.model = model
        self.optim = optim
        self.mesh = mesh
        self.n_micro = n_microbatches
        self.seed = seed
        self.n_stages = mesh.shape["pp"]
        if cfg.n_layers % self.n_stages:
            raise ValueError(
                f"n_layers={cfg.n_layers} must divide by pp={self.n_stages}")
        self.template = model.blocks[0]
        self._block_names = [b.name for b in model.blocks]
        # chunked head+loss on the last stage (same lever as
        # SpmdTrainer(loss_chunk=...): logits capped at (B, c, V))
        self.loss_chunk = loss_chunk
        self.params = None
        self.opt_state = None
        self._step_fn = None
        self._step_count = 0

    # -- param plumbing ------------------------------------------------ #
    def _rename(self, tree, src, dst):
        return {k.replace(src, dst): {kk: vv for kk, vv in v.items()}
                for k, v in tree.items()}

    def _split(self, params):
        """model params -> (rest, blocks-stacked-on-leading-layer-axis)."""
        block_prefixes = tuple(n + "." for n in self._block_names)
        rest = {k: v for k, v in params.items()
                if not k.startswith(block_prefixes)}
        per_block = []
        for name in self._block_names:
            sub = {k: v for k, v in params.items()
                   if k.startswith(name + ".")}
            per_block.append(self._rename(sub, name, self.template.name))
        stacked = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves), *per_block)
        return rest, stacked

    def merge(self):
        """Back to the model's flat params dict (host-side convenience)."""
        rest, stacked = self.params["rest"], self.params["blocks"]
        out = dict(rest)
        for i, name in enumerate(self._block_names):
            sub = jax.tree_util.tree_map(lambda l: l[i], stacked)
            out.update(self._rename(sub, self.template.name, name))
        return out

    # -- setup --------------------------------------------------------- #
    def _has_tp(self):
        return "tp" in self.mesh.axis_names and self.mesh.shape["tp"] > 1

    def _stacked_placement(self, blocks):
        """Placement specs for the layer-stacked block params: always
        P('pp') on the stacking axis; with a tp mesh axis the inner dims
        additionally take the template module's megatron layout (its
        ``pspec``) — tensor parallel INSIDE each pipeline stage."""
        if not self._has_tp():
            return jax.tree_util.tree_map(lambda _: P("pp"), blocks)
        from .spmd import _filter_spec     # drop axes absent from mesh
        by_mod = {m.name: getattr(m, "pspec", {})
                  for m in self.template.modules()}
        out = {}
        for mod_name, sub in blocks.items():
            ps = by_mod.get(mod_name, {})
            out[mod_name] = {
                k: (P("pp", *_filter_spec(ps[k], self.mesh))
                    if k in ps and ps[k] is not None else P("pp"))
                for k in sub}
        return out

    def init(self):
        from jax.sharding import NamedSharding
        model_params = self.model.init(jax.random.PRNGKey(self.seed))
        rest, blocks = self._split(model_params)
        put = lambda t, spec: jax.tree_util.tree_map(
            lambda l: jax.device_put(l, NamedSharding(self.mesh, spec)), t)
        blk_place = self._stacked_placement(blocks)
        self.params = {
            "rest": put(rest, P()),
            "blocks": jax.tree_util.tree_map(
                lambda l, sp: jax.device_put(
                    l, NamedSharding(self.mesh, sp)), blocks, blk_place,
                is_leaf=lambda v: not isinstance(v, dict))}
        self.opt_state = jax.jit(self.optim.init_state)(self.params)
        self._build()
        return self

    def _build(self):
        from ..models.transformer import (lm_cross_entropy,
                                          chunked_token_nll)
        from ..nn.module import Ctx
        model, template, optim = self.model, self.template, self.optim
        cfg = model.cfg
        n_micro, mesh = self.n_micro, self.mesh
        has_dp = "dp" in mesh.axis_names
        has_sp = "sp" in mesh.axis_names and mesh.shape["sp"] > 1
        loss_chunk = self.loss_chunk

        def local(rest, blocks_stage, tokens, targets):
            def loss_fn(rest, blocks_stage):
                ctx = Ctx(state={}, training=True, rng_key=None)
                h = model.embed.apply(rest, tokens, ctx)
                h = h.astype(jnp.dtype(cfg.dtype))
                mbs = h.reshape((n_micro, -1) + h.shape[1:])

                def stage_fn(stage_params, x):
                    def body(hh, blk):
                        c = Ctx(state={}, training=True, rng_key=None)
                        return template.apply(blk, hh, c), None
                    out, _ = lax.scan(body, x, stage_params)
                    return out

                outs = pipeline_run(stage_fn, blocks_stage, mbs, "pp")
                h_out = outs.reshape(h.shape)
                ctx2 = Ctx(state={}, training=True, rng_key=None)
                h_out = model.final_norm.apply(rest, h_out, ctx2)

                def head_fn(h_c):
                    return (model.head.apply(rest, h_c, ctx2)
                            if model.head is not None
                            else h_c @ rest[model.embed.name]["weight"].T)

                # same semantics as TransformerLM.token_nll: a chunk
                # covering the whole sequence means no chunking
                if loss_chunk and loss_chunk < h_out.shape[1]:
                    tot, cnt = chunked_token_nll(head_fn, h_out, targets,
                                                 loss_chunk)
                    loss = tot / jnp.maximum(cnt, 1.0)
                else:
                    loss = lm_cross_entropy(head_fn(h_out), targets)
                # differentiate the LOCAL masked contribution — putting a
                # psum inside the differentiated function would make every
                # rank seed a cotangent through it and scale all gradients
                # by n_stages; the value is psum'd after the grad call
                return loss * last_stage_mask("pp")

            loss, (g_rest, g_blocks) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(rest, blocks_stage)
            loss = lax.psum(loss, "pp")
            if has_dp:
                loss = lax.pmean(loss, "dp")
            # rest grads live on different ranks (embed on stage 0, final
            # norm + head on the last stage, zeros elsewhere): psum over
            # pp combines the disjoint contributions into the replicated
            # global gradient; block grads stay sharded per-stage
            g_rest = jax.tree_util.tree_map(
                lambda g: lax.psum(g, "pp"), g_rest)
            if has_dp:
                g_rest, g_blocks = jax.tree_util.tree_map(
                    lambda g: lax.pmean(g, "dp"), (g_rest, g_blocks))
            return loss, (g_rest, g_blocks)

        rest_specs = jax.tree_util.tree_map(lambda _: P(),
                                            self.params["rest"])
        blk_specs = jax.tree_util.tree_map(lambda _: P("pp"),
                                           self.params["blocks"])
        # in_specs may only mention MANUAL axes; auto-axis shardings (tp
        # on the stacked block params, sp on the token sequence dim) ride
        # on the arrays themselves (device_put in init()/step()) and
        # GSPMD propagates them
        tok_spec = P("dp") if has_dp else P()
        # with a tp and/or sp axis present, shard_map is manual over
        # pp/dp ONLY and tp/sp stay AUTO axes: XLA partitions each
        # stage's matmuls over tp (megatron layout from the template
        # pspecs) and the sequence dim over sp, inserting the collectives
        # — pp x tp / pp x sp composition without hand-written psums
        manual = None
        if self._has_tp() or has_sp:
            manual = {"pp"} | ({"dp"} if has_dp else set())
        mapped = _shard_map(
            local, mesh,
            (rest_specs, blk_specs, tok_spec, tok_spec),
            (P(), (rest_specs, blk_specs)),
            manual_axes=manual)

        def step(params, opt_state, tokens, targets):
            loss, (g_rest, g_blocks) = mapped(
                params["rest"], params["blocks"], tokens, targets)
            grads = {"rest": g_rest, "blocks": g_blocks}
            new_params, new_opt = optim.update(grads, params, opt_state)
            return new_params, new_opt, loss

        self._step_fn = jax.jit(step, donate_argnums=(0, 1))

    # -- API ----------------------------------------------------------- #
    def step(self, tokens, targets):
        if self._step_fn is None:
            self.init()
        from jax.sharding import NamedSharding
        n_dp = self.mesh.shape.get("dp", 1)
        batch = jnp.asarray(tokens).shape[0]
        if batch % n_dp:
            raise ValueError(f"batch {batch} must divide by dp={n_dp}")
        if (batch // n_dp) % self.n_micro:
            raise ValueError(
                f"per-dp-shard batch {batch // n_dp} must divide by "
                f"n_microbatches={self.n_micro}")
        has_dp = "dp" in self.mesh.axis_names
        has_sp = ("sp" in self.mesh.axis_names
                  and self.mesh.shape["sp"] > 1)
        if has_sp:
            seq = jnp.asarray(tokens).shape[1]
            n_sp = self.mesh.shape["sp"]
            if seq % n_sp:
                raise ValueError(
                    f"sequence length {seq} must divide by sp={n_sp}")
            # sp is an AUTO axis: the sequence sharding rides on the
            # array (in_specs inside the partial-manual shard_map may
            # only mention manual axes)
            spec = P("dp" if has_dp else None, "sp")
        else:
            spec = P("dp") if has_dp else P()
        sh = NamedSharding(self.mesh, spec)
        tokens = jax.device_put(jnp.asarray(tokens), sh)
        targets = jax.device_put(jnp.asarray(targets), sh)
        self.params, self.opt_state, loss = self._step_fn(
            self.params, self.opt_state, tokens, targets)
        self._step_count += 1
        return loss
