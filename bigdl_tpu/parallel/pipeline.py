"""Pipeline parallelism over the ``pp`` mesh axis.

GSPMD does not partition by *layer*; pipelining is inherently a manual
schedule, so this is a shard_map program: each pp rank holds one stage's
parameters (stacked layer params sharded on their leading axis), and a
``lax.scan`` runs the GPipe schedule — microbatches enter stage 0, flow
stage-to-stage via ``lax.ppermute`` (one ICI hop per tick), and leave from
the last stage.  With M microbatches and S stages the scan runs M + S - 1
ticks; every tick all stages compute concurrently (the bubble is the usual
(S-1)/(M+S-1)).

AD: ppermute transposes to the reverse rotation and the scan transposes to
the reverse schedule, so ``jax.grad`` through :func:`pipeline_run` is the
standard 1F1B-equivalent backward pipeline — no hand-written backward.

The reference has nothing comparable (Spark tasks parallelise over *data*
only); this is part of going beyond its scale (SURVEY §2 #30).
"""
from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ._compat import shard_map as _shard_map


def pipeline_run(stage_fn: Callable, stage_params, microbatches,
                 axis_name: str = "pp"):
    """Run the GPipe schedule. Call inside shard_map.

    stage_fn: (params_of_my_stage, x) -> y   (x, y same shape)
    stage_params: this rank's stage parameters (device-varying pytree)
    microbatches: (M, mb, ...) — the full microbatched input, replicated;
                  only stage 0 reads it.
    Returns (M, mb, ...) outputs, valid on the *last* stage (zeros
    elsewhere); weight per-stage reductions with :func:`last_stage_mask`.
    """
    n_stages = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    # shift-down (no wraparound): stage i -> i+1; stage 0 receives zeros
    perm = [(i, i + 1) for i in range(n_stages - 1)]
    is_first = (idx == 0)
    is_last = (idx == n_stages - 1)

    def tick(carry, t):
        state, outputs = carry
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        feed = lax.dynamic_index_in_dim(microbatches, mb_idx, 0,
                                        keepdims=False)
        x = jnp.where(is_first, feed, state)
        y = stage_fn(stage_params, x)
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        write = is_last & (t >= n_stages - 1)
        cur = lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, y, cur), out_idx, 0)
        state = lax.ppermute(y, axis_name, perm)
        return (state, outputs), None

    init = (jnp.zeros(mb_shape, microbatches.dtype),
            jnp.zeros((n_micro,) + mb_shape, microbatches.dtype))
    (_, outputs), _ = lax.scan(tick, init, jnp.arange(n_micro + n_stages - 1))
    return outputs


def last_stage_mask(axis_name: str = "pp"):
    """1.0 on the last pp rank, 0.0 elsewhere — multiply the loss by this
    and psum over pp so earlier stages contribute zero."""
    idx = lax.axis_index(axis_name)
    n = lax.axis_size(axis_name)
    return (idx == n - 1).astype(jnp.float32)


def pipelined(stage_fn: Callable, mesh: Mesh, n_microbatches: int,
              axis_name: str = "pp"):
    """Wrap a stage function into a global-array pipelined forward.

    Returns ``f(stacked_params, x)`` where stacked_params leaves have a
    leading n_stages axis (sharded over pp) and x is (batch, ...);
    the result is the full-model output (batch, ...), replicated.
    """
    def global_fn(stacked_params, x):
        def local(params_stack, xs):
            # my slice of the stacked layer params: leading dim 1 -> squeeze
            my = jax.tree_util.tree_map(lambda p: p[0], params_stack)
            mbs = xs.reshape((n_microbatches, -1) + xs.shape[1:])
            outs = pipeline_run(stage_fn, my, mbs, axis_name)
            outs = outs.reshape(xs.shape)
            # broadcast the last stage's result to every rank
            outs = lax.psum(outs * last_stage_mask(axis_name), axis_name)
            return outs

        in_specs = (jax.tree_util.tree_map(lambda _: P(axis_name),
                                           stacked_params), P())
        return _shard_map(local, mesh, in_specs, P())(stacked_params, x)

    return global_fn
