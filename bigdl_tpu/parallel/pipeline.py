"""Pipeline parallelism over the ``pp`` mesh axis.

GSPMD does not partition by *layer*; pipelining is inherently a manual
schedule, so this is a shard_map program: each pp rank holds one stage's
parameters (stacked layer params sharded on their leading axis), and a
``lax.scan`` runs the GPipe schedule — microbatches enter stage 0, flow
stage-to-stage via ``lax.ppermute`` (one ICI hop per tick), and leave from
the last stage.  With M microbatches and S stages the scan runs M + S - 1
ticks; every tick all stages compute concurrently (the bubble is the usual
(S-1)/(M+S-1)).

AD: ppermute transposes to the reverse rotation and the scan transposes to
the reverse schedule, so ``jax.grad`` through :func:`pipeline_run` is the
standard 1F1B-equivalent backward pipeline — no hand-written backward.

The reference has nothing comparable (Spark tasks parallelise over *data*
only); this is part of going beyond its scale (SURVEY §2 #30).
"""
from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ._compat import axis_size, shard_map as _shard_map


def pipeline_run(stage_fn: Callable, stage_params, microbatches,
                 axis_name: str = "pp"):
    """Run the GPipe schedule. Call inside shard_map.

    stage_fn: (params_of_my_stage, x) -> y   (x, y same shape)
    stage_params: this rank's stage parameters (device-varying pytree)
    microbatches: (M, mb, ...) — the full microbatched input, replicated;
                  only stage 0 reads it.
    Returns (M, mb, ...) outputs, valid on the *last* stage (zeros
    elsewhere); weight per-stage reductions with :func:`last_stage_mask`.
    """
    n_stages = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    # shift-down (no wraparound): stage i -> i+1; stage 0 receives zeros
    perm = [(i, i + 1) for i in range(n_stages - 1)]
    is_first = (idx == 0)
    is_last = (idx == n_stages - 1)

    def tick(carry, t):
        state, outputs = carry
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        feed = lax.dynamic_index_in_dim(microbatches, mb_idx, 0,
                                        keepdims=False)
        x = jnp.where(is_first, feed, state)
        y = stage_fn(stage_params, x)
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        write = is_last & (t >= n_stages - 1)
        cur = lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, y, cur), out_idx, 0)
        state = lax.ppermute(y, axis_name, perm)
        return (state, outputs), None

    init = (jnp.zeros(mb_shape, microbatches.dtype),
            jnp.zeros((n_micro,) + mb_shape, microbatches.dtype))
    (_, outputs), _ = lax.scan(tick, init, jnp.arange(n_micro + n_stages - 1))
    return outputs


def last_stage_mask(axis_name: str = "pp"):
    """1.0 on the last pp rank, 0.0 elsewhere — multiply the loss by this
    and psum over pp so earlier stages contribute zero."""
    idx = lax.axis_index(axis_name)
    n = axis_size(axis_name)
    return (idx == n - 1).astype(jnp.float32)


def pipelined(stage_fn: Callable, mesh: Mesh, n_microbatches: int,
              axis_name: str = "pp"):
    """Wrap a stage function into a global-array pipelined forward.

    Returns ``f(stacked_params, x)`` where stacked_params leaves have a
    leading n_stages axis (sharded over pp) and x is (batch, ...);
    the result is the full-model output (batch, ...), replicated.
    """
    def global_fn(stacked_params, x):
        def local(params_stack, xs):
            # my slice of the stacked layer params: leading dim 1 -> squeeze
            my = jax.tree_util.tree_map(lambda p: p[0], params_stack)
            mbs = xs.reshape((n_microbatches, -1) + xs.shape[1:])
            outs = pipeline_run(stage_fn, my, mbs, axis_name)
            outs = outs.reshape(xs.shape)
            # broadcast the last stage's result to every rank
            outs = lax.psum(outs * last_stage_mask(axis_name), axis_name)
            return outs

        in_specs = (jax.tree_util.tree_map(lambda _: P(axis_name),
                                           stacked_params), P())
        return _shard_map(local, mesh, in_specs, P())(stacked_params, x)

    return global_fn


# --------------------------------------------------------------------- #
# transformer pipeline trainer                                          #
# --------------------------------------------------------------------- #
class PipelineLMTrainer:
    """GPipe training for TransformerLM over a 'pp' mesh axis (x optional
    'dp', 'tp', 'sp'): each pp rank owns n_layers/n_stages blocks (params
    stacked on a leading layer axis, sharded over pp); microbatches flow
    through pipeline_run's ppermute schedule; embedding feeds stage 0 and
    the LM head + loss run on the last stage (loss is masked+psum'd, so
    AD routes every gradient to the stage that owns it).  tp and sp are
    AUTO (GSPMD) axes inside the manual pp/dp shard_map: tensor parallel
    via the megatron pspecs, sequence parallel by sharding the sequence
    dim of the token batch.

    By default the optimizer update happens on the global (sharded)
    arrays outside the shard_map — GSPMD keeps the pp layout for block
    params/moments.  The composed-mesh roofline knobs (all default-off,
    same semantics as ``DistriOptimizer``; see docs/distributed.md §
    Composed parallelism):

    ``zero1``        ZeRO-1 over the **dp axis of the pp(/tp)-sharded
                     model** (arXiv:2004.13336 composed with GPipe):
                     grads reduce-scatter into each stage's shard space
                     over dp, each (stage, dp-rank) updates only its
                     1/dp slice with its 1/dp moment shard — optimizer
                     state lives ``P(("pp", "dp"))``, 1/(pp·dp) per
                     device by sharding metadata — and updated params
                     ride an all-gather back.  Elementwise optimizers
                     only; grad-clip/health norms psum over the right
                     axis groups (rest over dp, blocks over dp×pp).
    ``bucket_bytes`` exchange dp-group gradients in flat single-dtype
                     buckets (one collective per bucket — the dp bucket
                     stream, accounted ``comm/group.dp.*``); with
                     ``zero1`` it sizes the flat shard-space buckets.
    ``compress``     "fp16"/"bf16" dp-group wire compression (the mean
                     travels, pre-scaled in fp32 — fp16-sum-safe).
    ``fused_optim``  route the update through the Pallas kernels
                     (``bigdl_tpu.kernels``) when the OptimMethod
                     supports ``fused``.
    ``overlap_grad_chunks``
                     split the microbatch train into this many gradient
                     chunks: each chunk runs its own GPipe schedule and
                     issues its dp-group collectives as soon as its
                     backward finishes — **under the next chunk's
                     pipeline bubble** instead of after the last
                     microbatch (XLA's async collectives overlap them
                     with the next chunk's compute).  Must divide
                     ``n_microbatches``.  Chunked accumulation
                     reassociates the token-mean (documented-ulp class,
                     see docs/checkpointing.md taxonomy).
    ``clip_norm``    global-L2 gradient clipping, axis-group-scoped on
                     the zero1 path (shard sums-of-squares psum'd over
                     dp for the replicated rest, dp×pp for the stage
                     shards).
    """

    def __init__(self, model, optim, mesh, n_microbatches=4, seed=0,
                 loss_chunk=None, zero1=False, bucket_bytes=None,
                 compress=None, fused_optim=False, overlap_grad_chunks=1,
                 clip_norm=None):
        if model.frozen_param_names():
            raise NotImplementedError(
                "Module.freeze is not supported by PipelineLMTrainer "
                "(block params are stacked per stage, losing per-module "
                "identity); unfreeze or use SpmdTrainer")
        cfg = model.cfg
        if cfg.dropout:
            raise ValueError("PipelineLMTrainer requires dropout=0.0")
        if "pp" not in mesh.axis_names:
            raise ValueError("mesh needs a 'pp' axis")
        self.model = model
        self.optim = optim
        self.mesh = mesh
        self.n_micro = n_microbatches
        self.seed = seed
        self.n_stages = mesh.shape["pp"]
        if cfg.n_layers % self.n_stages:
            raise ValueError(
                f"n_layers={cfg.n_layers} must divide by pp={self.n_stages}")
        n_dp = mesh.shape.get("dp", 1)
        if (zero1 or bucket_bytes or compress) and n_dp < 2:
            raise ValueError(
                "zero1/bucket_bytes/compress drive the dp-group gradient "
                f"exchange: the mesh needs a dp axis > 1 (got dp={n_dp})")
        if compress not in (None, "fp16", "float16", "bf16", "bfloat16"):
            # a typo'd mode would silently train at full fp32 wire
            raise ValueError(
                f"unknown compress mode {compress!r} "
                "(fp16/float16/bf16/bfloat16)")
        if zero1:
            from ..optim.optim_method import LAMB, LARS
            if isinstance(optim, (LARS, LAMB)):
                raise ValueError(
                    f"zero1 cannot shard {type(optim).__name__}: its "
                    "per-TENSOR trust ratios need whole-tensor norms, "
                    "and a dim-0 shard's norm is not the tensor's norm")
        self.zero1 = bool(zero1)
        self.bucket_bytes = bucket_bytes
        self.compress = compress
        self.clip_norm = clip_norm
        if fused_optim:
            if not hasattr(optim, "fused"):
                raise ValueError(
                    f"fused_optim=True: {type(optim).__name__} has no "
                    "fused kernel (supported: SGD, Adam, AdamW)")
            import copy
            # shallow copy, never mutate the user's instance (reuse
            # elsewhere without the flag keeps the default path)
            self.optim = optim = copy.copy(optim)
            optim.fused = True
        self.fused_optim = bool(fused_optim)
        self.overlap_chunks = int(overlap_grad_chunks)
        if self.overlap_chunks < 1 or n_microbatches % self.overlap_chunks:
            raise ValueError(
                f"overlap_grad_chunks={overlap_grad_chunks} must be >= 1 "
                f"and divide n_microbatches={n_microbatches}")
        self.template = model.blocks[0]
        self._block_names = [b.name for b in model.blocks]
        # chunked head+loss on the last stage (same lever as
        # SpmdTrainer(loss_chunk=...): logits capped at (B, c, V))
        self.loss_chunk = loss_chunk
        self.params = None
        self.opt_state = None
        self._step_fn = None
        self._step_count = 0
        self._recorder = None
        self._telemetry_health = True
        self._with_health = False
        self._seen_sigs = set()
        self._z1_rest = None
        self._z1_blocks = None

    # -- param plumbing ------------------------------------------------ #
    def _rename(self, tree, src, dst):
        return {k.replace(src, dst): {kk: vv for kk, vv in v.items()}
                for k, v in tree.items()}

    def _split(self, params):
        """model params -> (rest, blocks-stacked-on-leading-layer-axis)."""
        block_prefixes = tuple(n + "." for n in self._block_names)
        rest = {k: v for k, v in params.items()
                if not k.startswith(block_prefixes)}
        per_block = []
        for name in self._block_names:
            sub = {k: v for k, v in params.items()
                   if k.startswith(name + ".")}
            per_block.append(self._rename(sub, name, self.template.name))
        stacked = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves), *per_block)
        return rest, stacked

    def merge(self):
        """Back to the model's flat params dict (host-side convenience)."""
        rest, stacked = self.params["rest"], self.params["blocks"]
        out = dict(rest)
        for i, name in enumerate(self._block_names):
            sub = jax.tree_util.tree_map(lambda l: l[i], stacked)
            out.update(self._rename(sub, self.template.name, name))
        return out

    # -- setup --------------------------------------------------------- #
    def _has_tp(self):
        return "tp" in self.mesh.axis_names and self.mesh.shape["tp"] > 1

    def _stacked_placement(self, blocks):
        """Placement specs for the layer-stacked block params: always
        P('pp') on the stacking axis; with a tp mesh axis the inner dims
        additionally take the template module's megatron layout (its
        ``pspec``) — tensor parallel INSIDE each pipeline stage."""
        if not self._has_tp():
            return jax.tree_util.tree_map(lambda _: P("pp"), blocks)
        from .spmd import _filter_spec     # drop axes absent from mesh
        by_mod = {m.name: getattr(m, "pspec", {})
                  for m in self.template.modules()}
        out = {}
        for mod_name, sub in blocks.items():
            ps = by_mod.get(mod_name, {})
            out[mod_name] = {
                k: (P("pp", *_filter_spec(ps[k], self.mesh))
                    if k in ps and ps[k] is not None else P("pp"))
                for k in sub}
        return out

    def init(self):
        from jax.sharding import NamedSharding
        model_params = self.model.init(jax.random.PRNGKey(self.seed))
        rest, blocks = self._split(model_params)
        put = lambda t, spec: jax.tree_util.tree_map(
            lambda l: jax.device_put(l, NamedSharding(self.mesh, spec)), t)
        blk_place = self._stacked_placement(blocks)
        self.params = {
            "rest": put(rest, P()),
            "blocks": jax.tree_util.tree_map(
                lambda l, sp: jax.device_put(
                    l, NamedSharding(self.mesh, sp)), blocks, blk_place,
                is_leaf=lambda v: not isinstance(v, dict))}
        if self.zero1:
            self.opt_state = self._init_zero1_state(rest, blocks)
        else:
            self.opt_state = jax.jit(self.optim.init_state)(self.params)
        self._build()
        return self

    # -- zero1 over the dp axis of the pp-sharded model ----------------- #
    def _init_zero1_state(self, rest, blocks):
        """Shard-space optimizer state for the composed zero1 path.

        Two layouts, because a flat bucket must never mix pp-replicated
        and pp-varying leaves: ``rest`` (embed/norm/head — identical on
        every stage) sharded 1/dp, and the per-STAGE slice of the
        stacked blocks sharded 1/dp within each stage.  The outside-jit
        storage stacks every stage's shard space on dim 0, placed
        ``P(("pp", "dp"))`` — by sharding metadata each device holds
        exactly 1/(pp·dp) of the block moments, the composed-mesh
        memory claim."""
        from jax.sharding import NamedSharding
        from ..optim.distri_optimizer import fsdp_opt_state_specs
        from .zero import Zero1Layout
        n_dp = self.mesh.shape["dp"]
        S = self.n_stages
        local_blocks = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(
                (l.shape[0] // S,) + tuple(l.shape[1:]), l.dtype), blocks)
        self._z1_rest = Zero1Layout(rest, n_dp,
                                    bucket_bytes=self.bucket_bytes)
        self._z1_blocks = Zero1Layout(local_blocks, n_dp,
                                      bucket_bytes=self.bucket_bytes)
        space_r = self._z1_rest.stacked_space_zeros(1)
        space_b = self._z1_blocks.stacked_space_zeros(S)
        every = lambda t: jax.tree_util.tree_map(lambda _: True, t)
        self._o_specs = {
            "rest": fsdp_opt_state_specs(space_r, every(space_r),
                                         self.optim, spec=P("dp")),
            "blocks": fsdp_opt_state_specs(space_b, every(space_b),
                                           self.optim,
                                           spec=P(("pp", "dp")))}
        state = {"rest": jax.jit(self.optim.init_state)(space_r),
                 "blocks": jax.jit(self.optim.init_state)(space_b)}
        return jax.tree_util.tree_map(
            lambda l, sp: jax.device_put(l, NamedSharding(self.mesh, sp)),
            state, self._o_specs)

    def _telemetry_active(self):
        return (self._recorder is not None and self._recorder.enabled
                and self._telemetry_health)

    def _build(self):
        from ..models.transformer import lm_token_nll, chunked_token_nll
        from ..nn.module import Ctx
        from ..optim.optimizer import _tree_nonfinite, _tree_sq
        from .allreduce import allreduce_gradients
        from .bucketer import GradBucketer
        model, template, optim = self.model, self.template, self.optim
        cfg = model.cfg
        n_micro, mesh = self.n_micro, self.mesh
        has_dp = "dp" in mesh.axis_names
        has_sp = "sp" in mesh.axis_names and mesh.shape["sp"] > 1
        loss_chunk = self.loss_chunk
        zero1 = self.zero1
        compress = self.compress
        clip_norm = self.clip_norm
        n_chunks = self.overlap_chunks
        z1r, z1b = self._z1_rest, self._z1_blocks
        telemetry = self._telemetry_active()
        self._with_health = telemetry
        self._seen_sigs.clear()
        rec = self._recorder
        if rec is not None and rec.enabled:
            # re-traces re-report the trace-time accounting: reset the
            # per-step gauge families so a rebuild never double-counts
            rec.reset_gauges("collective/")
            rec.reset_gauges("comm/group.")
        bucketer_rest = bucketer_blocks = None
        if self.bucket_bytes and not zero1:
            # two dp bucket streams — one per param family — so a flat
            # bucket never mixes pp-replicated rest leaves with
            # pp-varying stage leaves (templates from the placed params:
            # _build always runs after init() placed them)
            S = self.n_stages
            local_blocks_t = jax.tree_util.tree_map(
                lambda l: jax.ShapeDtypeStruct(
                    (l.shape[0] // S,) + tuple(l.shape[1:]), l.dtype),
                self.params["blocks"])
            bucketer_rest = GradBucketer(self.params["rest"],
                                         bucket_bytes=self.bucket_bytes)
            bucketer_blocks = GradBucketer(local_blocks_t,
                                           bucket_bytes=self.bucket_bytes)

        def chunk_loss(rest, blocks_stage, tokens_c, targets_c, m_chunk):
            """(masked total NLL on the last stage, grads wrt rest and
            this stage's blocks) for one gradient chunk of microbatches.
            Differentiates the LOCAL masked total — a psum inside the
            differentiated function would make every rank seed a
            cotangent through it and scale all gradients by n_stages;
            values are psum'd after the grad call."""
            def loss_fn(rest, blocks_stage):
                ctx = Ctx(state={}, training=True, rng_key=None)
                h = model.embed.apply(rest, tokens_c, ctx)
                h = h.astype(jnp.dtype(cfg.dtype))
                mbs = h.reshape((m_chunk, -1) + h.shape[1:])

                def stage_fn(stage_params, x):
                    def body(hh, blk):
                        c = Ctx(state={}, training=True, rng_key=None)
                        return template.apply(blk, hh, c), None
                    out, _ = lax.scan(body, x, stage_params)
                    return out

                outs = pipeline_run(stage_fn, blocks_stage, mbs, "pp")
                h_out = outs.reshape(h.shape)
                ctx2 = Ctx(state={}, training=True, rng_key=None)
                h_out = model.final_norm.apply(rest, h_out, ctx2)

                def head_fn(h_c):
                    return (model.head.apply(rest, h_c, ctx2)
                            if model.head is not None
                            else h_c @ rest[model.embed.name]["weight"].T)

                # same semantics as TransformerLM.token_nll: a chunk
                # covering the whole sequence means no chunking
                if loss_chunk and loss_chunk < h_out.shape[1]:
                    tot, _ = chunked_token_nll(head_fn, h_out, targets_c,
                                               loss_chunk)
                else:
                    tot, _ = lm_token_nll(head_fn(h_out), targets_c)
                return tot * last_stage_mask("pp")

            return jax.value_and_grad(loss_fn, argnums=(0, 1))(
                rest, blocks_stage)

        def exchange(g_rest, g_blocks):
            """One gradient chunk's collectives: pp-group psum of the
            stage-disjoint rest grads, then the dp-group exchange —
            issued HERE, per chunk, so XLA's async scheduler can launch
            them under the next chunk's pipeline compute instead of
            serializing every exchange behind the last microbatch.
            Returns (rest, blocks) grads — shard-space trees on the
            zero1 path, replicated/per-stage trees otherwise."""
            # rest grads live on different ranks (embed on stage 0,
            # final norm + head on the last stage, zeros elsewhere):
            # psum over pp combines the disjoint contributions into the
            # replicated global gradient; block grads stay per-stage
            g_rest = allreduce_gradients(g_rest, "pp", mean=False,
                                         group="pp")
            if not has_dp:
                return g_rest, g_blocks
            if zero1:
                return (z1r.scatter_grads(g_rest, "dp",
                                          compress=compress),
                        z1b.scatter_grads(g_blocks, "dp",
                                          compress=compress))
            if bucketer_rest is not None:
                return (bucketer_rest.allreduce(g_rest, "dp",
                                                compress=compress),
                        bucketer_blocks.allreduce(g_blocks, "dp",
                                                  compress=compress))
            return (allreduce_gradients(g_rest, "dp", compress=compress),
                    allreduce_gradients(g_blocks, "dp",
                                        compress=compress))

        def grads_and_loss(rest, blocks_stage, tokens, targets):
            """Chunked GPipe fwd/bwd + per-chunk collective issue.
            Returns (local mean loss, exchanged rest grads, exchanged
            block grads) — grads carry the 1/valid-token mean weighting,
            applied per chunk BEFORE the exchange so a compressed wire
            ships bounded per-token-scale values."""
            rows = tokens.shape[0]
            m_chunk = n_micro // n_chunks
            if rows % n_chunks:
                # unreachable via step() (which gates rows % n_micro,
                # and n_chunks | n_micro), but a direct _step_fn caller
                # must never silently drop the tail rows
                raise ValueError(
                    f"local batch {rows} must divide by "
                    f"overlap_grad_chunks={n_chunks}")
            rows_c = rows // n_chunks
            # the mean denominator (valid-token count) is param-free:
            # computed up front so per-chunk grads can be final-scaled
            cnt = jnp.maximum(
                jnp.sum((targets != -1).astype(jnp.float32)), 1.0)
            tot_acc, gr_acc, gb_acc = 0.0, None, None
            add = lambda a, b: a + b
            for k in range(n_chunks):
                tok_c = lax.slice_in_dim(tokens, k * rows_c,
                                         (k + 1) * rows_c, axis=0)
                tgt_c = lax.slice_in_dim(targets, k * rows_c,
                                         (k + 1) * rows_c, axis=0)
                tot, (g_rest, g_blocks) = chunk_loss(
                    rest, blocks_stage, tok_c, tgt_c, m_chunk)
                scale = lambda g: g / cnt
                g_rest = jax.tree_util.tree_map(scale, g_rest)
                g_blocks = jax.tree_util.tree_map(scale, g_blocks)
                g_rest, g_blocks = exchange(g_rest, g_blocks)
                tot_acc = tot_acc + tot
                if gr_acc is None:
                    gr_acc, gb_acc = g_rest, g_blocks
                else:
                    gr_acc = jax.tree_util.tree_map(add, gr_acc, g_rest)
                    gb_acc = jax.tree_util.tree_map(add, gb_acc, g_blocks)
            loss = lax.psum(tot_acc / cnt, "pp")
            if has_dp:
                loss = lax.pmean(loss, "dp")
            return loss, gr_acc, gb_acc

        def group_sq(fn, r, b, sharded):
            """Axis-group-scoped global reduction: the rest family is
            pp-REPLICATED (its zero1 dp shards psum over dp only — a pp
            psum would count it n_stages times), the block family varies
            over pp AND dp (psum over both on the zero1 shard space;
            over pp alone on the replicated-grad path)."""
            sr, sb = fn(r), fn(b)
            if sharded:             # zero1 shard space: 1/dp slices
                sr = lax.psum(sr, "dp")
                sb = lax.psum(sb, ("dp", "pp") if has_dp else "pp")
            else:
                sb = lax.psum(sb, "pp")
            return sr + sb

        def scoped_health(g_r, g_b, old_r, old_b, new_r, new_b, sharded):
            """health_scalars with per-axis-group psum scoping (the
            composed-mesh variant of optimizer.health_scalars)."""
            gn = jnp.sqrt(group_sq(_tree_sq, g_r, g_b, sharded))
            pn = jnp.sqrt(group_sq(_tree_sq, new_r, new_b, sharded))
            d = lambda a, o: jax.tree_util.tree_map(
                lambda x, y: x.astype(jnp.float32) - y.astype(jnp.float32),
                a, o)
            un = jnp.sqrt(group_sq(_tree_sq, d(new_r, old_r),
                                   d(new_b, old_b), sharded))
            return {"grad_norm": gn, "param_norm": pn, "update_norm": un,
                    "update_ratio": un / jnp.maximum(pn, 1e-12),
                    "nonfinite_grads": group_sq(_tree_nonfinite, g_r,
                                                g_b, sharded)}

        def clip(g_r, g_b, sharded):
            """Global-L2 clip with the same axis-group scoping."""
            total = jnp.sqrt(group_sq(_tree_sq, g_r, g_b, sharded))
            scale = jnp.minimum(1.0,
                                clip_norm / jnp.maximum(total, 1e-12))
            s = lambda g: g * scale
            return (jax.tree_util.tree_map(s, g_r),
                    jax.tree_util.tree_map(s, g_b))

        rest_specs = jax.tree_util.tree_map(lambda _: P(),
                                            self.params["rest"])
        blk_specs = jax.tree_util.tree_map(lambda _: P("pp"),
                                           self.params["blocks"])
        # in_specs may only mention MANUAL axes; auto-axis shardings (tp
        # on the stacked block params, sp on the token sequence dim) ride
        # on the arrays themselves (device_put in init()/step()) and
        # GSPMD propagates them
        tok_spec = P("dp") if has_dp else P()
        # with a tp and/or sp axis present, shard_map is manual over
        # pp/dp ONLY and tp/sp stay AUTO axes: XLA partitions each
        # stage's matmuls over tp (megatron layout from the template
        # pspecs) and the sequence dim over sp, inserting the collectives
        # — pp x tp / pp x sp composition without hand-written psums
        manual = None
        if self._has_tp() or has_sp:
            manual = {"pp"} | ({"dp"} if has_dp else set())

        if zero1:
            # the whole step — fwd/bwd, dp scatter, 1/dp-sharded update,
            # dp gather — runs inside ONE shard_map: each (stage,
            # dp-rank) touches only its shard-space slice of params and
            # moments; tp/sp stay AUTO inside (the update is
            # elementwise, trivially partitionable)
            def local(rest, blocks_stage, opt_r, opt_b, tokens, targets):
                loss, gsh_r, gsh_b = grads_and_loss(rest, blocks_stage,
                                                    tokens, targets)
                if clip_norm is not None:
                    gsh_r, gsh_b = clip(gsh_r, gsh_b, sharded=True)
                idx = lax.axis_index("dp")
                psh_r = z1r.local_shard(rest, idx)
                psh_b = z1b.local_shard(blocks_stage, idx)
                new_pr, new_or = optim.update(gsh_r, psh_r, opt_r)
                new_pb, new_ob = optim.update(gsh_b, psh_b, opt_b)
                new_rest = z1r.gather_params(new_pr, "dp")
                new_blocks = z1b.gather_params(new_pb, "dp")
                out = (loss, new_rest, new_blocks, new_or, new_ob)
                if telemetry:
                    out += (scoped_health(gsh_r, gsh_b, psh_r, psh_b,
                                          new_pr, new_pb, sharded=True),)
                return out

            out_specs = (P(), rest_specs, blk_specs,
                         self._o_specs["rest"], self._o_specs["blocks"])
            if telemetry:
                out_specs += (P(),)
            mapped = _shard_map(
                local, mesh,
                (rest_specs, blk_specs, self._o_specs["rest"],
                 self._o_specs["blocks"], tok_spec, tok_spec),
                out_specs, manual_axes=manual)

            def step(params, opt_state, tokens, targets):
                out = mapped(params["rest"], params["blocks"],
                             opt_state["rest"], opt_state["blocks"],
                             tokens, targets)
                loss, new_rest, new_blocks, new_or, new_ob = out[:5]
                res = ({"rest": new_rest, "blocks": new_blocks},
                       {"rest": new_or, "blocks": new_ob}, loss)
                if telemetry:
                    res += (out[5],)
                return res
        else:
            def local(rest, blocks_stage, tokens, targets):
                loss, g_rest, g_blocks = grads_and_loss(
                    rest, blocks_stage, tokens, targets)
                if clip_norm is not None:
                    g_rest, g_blocks = clip(g_rest, g_blocks,
                                            sharded=False)
                out = (loss, (g_rest, g_blocks))
                if telemetry:
                    out += (scoped_health(g_rest, g_blocks, rest,
                                          blocks_stage, rest,
                                          blocks_stage, sharded=False),)
                return out

            out_specs = (P(), (rest_specs, blk_specs))
            if telemetry:
                out_specs += (P(),)
            mapped = _shard_map(
                local, mesh,
                (rest_specs, blk_specs, tok_spec, tok_spec),
                out_specs, manual_axes=manual)

            def step(params, opt_state, tokens, targets):
                out = mapped(params["rest"], params["blocks"], tokens,
                             targets)
                loss, (g_rest, g_blocks) = out[:2]
                grads = {"rest": g_rest, "blocks": g_blocks}
                new_params, new_opt = optim.update(grads, params,
                                                   opt_state)
                res = (new_params, new_opt, loss)
                if telemetry:
                    # grad-norm scalars come from inside the shard_map
                    # (scoped psums; param/update norms there use the
                    # PRE-update params — the post-update norms the
                    # sentinel wants are refined below on the global
                    # arrays, where auto-jit reductions are global)
                    health = dict(out[2])
                    pn = jnp.sqrt(sum(
                        jnp.sum(l.astype(jnp.float32) ** 2)
                        for l in jax.tree_util.tree_leaves(new_params)))
                    un = jnp.sqrt(sum(
                        jnp.sum((a.astype(jnp.float32)
                                 - b.astype(jnp.float32)) ** 2)
                        for a, b in zip(
                            jax.tree_util.tree_leaves(new_params),
                            jax.tree_util.tree_leaves(params))))
                    health["param_norm"] = pn
                    health["update_norm"] = un
                    health["update_ratio"] = un / jnp.maximum(pn, 1e-12)
                    res += (health,)
                return res

        self._step_fn = jax.jit(step, donate_argnums=(0, 1))

    # -- telemetry ------------------------------------------------------ #
    def set_telemetry(self, recorder, health: bool = True):
        """Attach an observability Recorder (same contract as
        ``SpmdTrainer.set_telemetry``): each step() emits a step record
        (h2d / train_step spans with recompile detection; loss and
        tokens/sec scalars, plus the axis-group-scoped grad/param/update
        norms when ``health`` — the health variant changes the compiled
        program).  Re-jits without losing training progress when called
        after ``init()``.  Also installs ``recorder`` as the
        process-active one, so the trace-time ``comm/group.<axis>.*``
        accounting of the dp/pp exchanges lands in the same ring."""
        from ..observability import set_recorder
        self._recorder = recorder
        self._telemetry_health = bool(health)
        set_recorder(recorder)
        if (self._step_fn is not None
                and self._with_health != self._telemetry_active()):
            self._step_fn = None
            self._build()
        return self

    def _rec(self):
        if self._recorder is not None:
            return self._recorder
        from ..observability import null_recorder
        return null_recorder()

    # -- API ----------------------------------------------------------- #
    def step(self, tokens, targets):
        if self._step_fn is None:
            self.init()
        from jax.sharding import NamedSharding
        n_dp = self.mesh.shape.get("dp", 1)
        batch = jnp.asarray(tokens).shape[0]
        if batch % n_dp:
            raise ValueError(f"batch {batch} must divide by dp={n_dp}")
        if (batch // n_dp) % self.n_micro:
            raise ValueError(
                f"per-dp-shard batch {batch // n_dp} must divide by "
                f"n_microbatches={self.n_micro}")
        has_dp = "dp" in self.mesh.axis_names
        has_sp = ("sp" in self.mesh.axis_names
                  and self.mesh.shape["sp"] > 1)
        if has_sp:
            seq = jnp.asarray(tokens).shape[1]
            n_sp = self.mesh.shape["sp"]
            if seq % n_sp:
                raise ValueError(
                    f"sequence length {seq} must divide by sp={n_sp}")
            # sp is an AUTO axis: the sequence sharding rides on the
            # array (in_specs inside the partial-manual shard_map may
            # only mention manual axes)
            spec = P("dp" if has_dp else None, "sp")
        else:
            spec = P("dp") if has_dp else P()
        sh = NamedSharding(self.mesh, spec)
        rec = self._rec()
        rec.start_step(self._step_count)
        with rec.span("h2d"):
            tokens = jax.device_put(jnp.asarray(tokens), sh)
            targets = jax.device_put(jnp.asarray(targets), sh)
        span_name = "train_step"
        if rec.enabled:
            sig = (tuple(tokens.shape), str(tokens.dtype),
                   tuple(targets.shape), str(targets.dtype))
            if sig not in self._seen_sigs:
                self._seen_sigs.add(sig)
                span_name = "train_step_compile"
                rec.scalar("recompile", 1.0)
                # a new signature re-TRACES: the trace-time accounting
                # re-reports, and the accumulate-semantics group gauges
                # would double-count without a reset here
                rec.reset_gauges("collective/")
                rec.reset_gauges("comm/group.")
        with rec.span(span_name):
            out = self._step_fn(self.params, self.opt_state, tokens,
                                targets)
        if self._with_health:
            self.params, self.opt_state, loss, health = out
        else:
            self.params, self.opt_state, loss = out
            health = None
        self._step_count += 1
        if rec.enabled:
            wire = rec.gauge_value("collective/wire_bytes_per_step")
            if wire:
                rec.inc("collective/wire_bytes_total", wire)
            n_tok = int(np.prod(np.shape(tokens)))
            rec.inc("tokens_total", n_tok)
            rec.scalar("records", n_tok)
            rec.scalar("loss", loss)
            if health:
                for k, v in health.items():
                    rec.scalar(k, v)
            rec.end_step(self._step_count - 1)
        return loss
