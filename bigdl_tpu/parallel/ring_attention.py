"""Ring attention: sequence-parallel exact attention over the ``sp`` axis.

The reference framework scales sequence models only by unrolling RNNs
(nn/Recurrent.scala); long-context attention is beyond its scale.  Here the
sequence dimension is sharded over the mesh ``sp`` axis and full (exact)
attention is computed by rotating key/value chunks around the ring with
``lax.ppermute`` — each hop rides a single ICI neighbour link while the
local chunk's flash-attention block computes, and the online-softmax
accumulators (acc, m, l) merge chunks in any arrival order.

Must be called *inside* ``shard_map`` (or pmap) with q, k, v sharded over
``axis_name`` on their sequence dimension.  Causal masking is handled with
global token positions derived from ``lax.axis_index``, so cross-chunk
causality is exact.  Differentiable: AD transposes the ppermute ring into
the reverse rotation (the backward ring pass of the ring-attention paper).

Use :func:`ring_attention_shmap` to call it on globally-sharded arrays from
inside a jit/GSPMD region.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.flash_attention import (chunk_merge, chunk_merge_blockwise,
                                   finalize, DEFAULT_MASK_VALUE)
from ._compat import axis_size, shard_map as _shard_map


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   sm_scale: Optional[float] = None,
                   block_k: Optional[int] = 1024):
    """Exact attention with seq sharded over ``axis_name``.

    q, k, v: (batch, heads, seq_local, head_dim) — the local shard.
    Returns the local shard of the attention output, same shape as q.
    ``block_k`` caps the held chunk's score-matrix width (flash-style
    sub-blocking) so memory stays O(s_local * block_k) at long context;
    ``None`` merges each chunk in one piece.
    """
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    sp = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    s_total = sp * s_local
    q_pos = idx * s_local + jnp.arange(s_local)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def merge(k_c, v_c, acc, m, l, k_pos):
        if block_k is None:
            return chunk_merge(q, k_c, v_c, acc, m, l, q_pos, k_pos,
                               s_total, sm_scale, causal)
        return chunk_merge_blockwise(q, k_c, v_c, acc, m, l, q_pos, k_pos,
                                     s_total, sm_scale, causal,
                                     block_k=block_k)

    def step(carry, t):
        k_c, v_c, acc, m, l = carry
        src = (idx - t) % sp                 # origin rank of the held chunk
        k_pos = src * s_local + jnp.arange(s_local)
        if causal:
            # a chunk strictly in this rank's future contributes nothing;
            # skip its FLOPs entirely (per-device scalar cond)
            acc, m, l = lax.cond(
                src > idx,
                lambda a, mm, ll: (a, mm, ll),
                lambda a, mm, ll: merge(k_c, v_c, a, mm, ll, k_pos),
                acc, m, l)
        else:
            acc, m, l = merge(k_c, v_c, acc, m, l, k_pos)
        # rotate while (in a real schedule, overlapping) the next compute;
        # after sp hops k/v are home again, which keeps AD symmetric.
        k_c = lax.ppermute(k_c, axis_name, perm)
        v_c = lax.ppermute(v_c, axis_name, perm)
        return (k_c, v_c, acc, m, l), None

    init = (k, v,
            jnp.zeros((b, h, s_local, d), jnp.float32),
            jnp.full((b, h, s_local), DEFAULT_MASK_VALUE, jnp.float32),
            jnp.zeros((b, h, s_local), jnp.float32))
    (_, _, acc, m, l), _ = lax.scan(step, init, jnp.arange(sp))
    out, _ = finalize(acc, m, l)
    return out.astype(q.dtype)


def ring_attention_shmap(q, k, v, mesh: Mesh, causal: bool = False,
                         sm_scale: Optional[float] = None,
                         batch_axis: Optional[str] = "dp",
                         head_axis: Optional[str] = "tp",
                         seq_axis: str = "sp",
                         block_k: Optional[int] = 1024):
    """shard_map wrapper: (B, H, S, D) global arrays, batch over ``dp``,
    heads over ``tp``, sequence over ``sp``.  Heads are embarrassingly
    parallel, so tensor parallelism needs no collective here; only the
    sp ring communicates."""
    if seq_axis not in mesh.axis_names:
        raise ValueError(
            f"ring_attention_shmap: seq_axis {seq_axis!r} is not a mesh "
            f"axis {mesh.axis_names}; for unsharded sequences use "
            "ops.flash_attention instead")

    def ax(name):
        return name if name and name in mesh.axis_names else None

    spec = P(ax(batch_axis), ax(head_axis), ax(seq_axis), None)
    fn = partial(ring_attention, axis_name=seq_axis, causal=causal,
                 sm_scale=sm_scale, block_k=block_k)
    return _shard_map(fn, mesh, (spec, spec, spec), spec)(q, k, v)
