"""bigdl_tpu.parallel — mesh engine & collectives
(≙ utils/Engine.scala + parameters/ package)."""
from .mesh import (create_mesh, get_mesh, set_mesh, data_sharding,
                   replicated, shard_batch, init_distributed,
                   parse_template, DATA_AXES, MODEL_AXES)
from .compose import ComposedConfig, build_trainer
from .allreduce import (allreduce_gradients, reduce_scatter_gradients,
                        allgather_params, shardable_mask_dim0)
from .bucketer import GradBucketer
from .zero import Zero1Layout, Zero1Optim
from .ring_attention import ring_attention, ring_attention_shmap
from .pipeline import pipeline_run, pipelined
from .spmd import SpmdTrainer
