"""Device-mesh engine (≙ utils/Engine.scala + the Spark cluster runtime).

The reference Engine manages executor/core topology for Spark;
here the topology is a `jax.sharding.Mesh` over TPU chips.  Axes:

  dp    data parallel        (gradient psum — the DistriOptimizer all-reduce)
  fsdp  sharded data parallel (reduce_scatter + all_gather, ≙ the reference's
                               *partitioned* AllReduceParameter parameter server)
  tp    tensor parallel      (megatron-style sharded matmuls)
  sp    sequence parallel    (ring attention over ICI)
  pp    pipeline parallel    (microbatched ppermute stages)
  ep    expert parallel      (MoE experts; dispatch/combine all-to-all)

Axis order puts dp outermost so its collectives ride DCN across hosts while
tp/sp stay on intra-slice ICI (the usual pod layout).  On a single host the
mesh spans the local chips; `virtual_devices(n)` gives an n-device CPU mesh
for tests (xla_force_host_platform_device_count).
"""
from __future__ import annotations

import os
import re
from typing import Dict, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


_current_mesh: Optional[Mesh] = None

# canonical axis vocabulary of the composed-mesh templates.  DATA axes
# re-batch the same math (cheap to re-plan elastically); MODEL axes are
# entangled with tensor layouts (expensive re-partitions) — the split
# elastic/plan.py's shrink costs and checkpoint resharding advice key on.
KNOWN_AXES = ("dp", "fsdp", "sp", "tp", "pp", "ep")
DATA_AXES = ("dp", "fsdp")
MODEL_AXES = ("sp", "tp", "pp", "ep")

_TEMPLATE_RE = re.compile(r"([a-z]+)\s*[=:]?\s*(\d+)")


def parse_template(template) -> Dict[str, int]:
    """One declarative composed-mesh spelling -> ordered ``{axis: size}``.

    Accepts a dict (returned normalized), or a string in any of the
    usual spellings — ``"dp2x tp2 x pp2"``, ``"dp2,tp2,pp2"``,
    ``"dp=2 tp=2 pp=2"``, ``"dp2×tp2×pp2"``.  Axis names must come from
    the known vocabulary (catches ``pd2`` typos that would otherwise
    build a mesh no PartitionSpec mentions); sizes must be >= 1.
    """
    if isinstance(template, dict):
        pairs = [(str(k), int(v)) for k, v in template.items()]
    else:
        s = str(template).strip().lower()
        # an 'x'/'×' right after a size digit is a separator, not the
        # start of the next axis name — 'dp4xtp2' must parse as
        # dp4 × tp2, never reject as "unknown axis 'xtp'"
        s = re.sub(r"(?<=\d)\s*[x×*,]+\s*", " ", s)
        pairs = [(n, int(v)) for n, v in _TEMPLATE_RE.findall(s)]
        # every non-separator character must be consumed by some match:
        # "dpp2" silently parsing as dp... must fail instead
        leftover = _TEMPLATE_RE.sub("", s)
        if not pairs or leftover.strip(" ,x×*") != "":
            raise ValueError(
                f"unparseable mesh template {template!r} (expected "
                "e.g. 'dp2,tp2,pp2' or 'dp=2 x tp=2')")
    out: Dict[str, int] = {}
    for name, size in pairs:
        if name not in KNOWN_AXES:
            raise ValueError(
                f"unknown mesh axis {name!r} in template {template!r} "
                f"(known: {', '.join(KNOWN_AXES)})")
        if name in out:
            raise ValueError(f"duplicate axis {name!r} in {template!r}")
        if size < 1:
            raise ValueError(f"axis {name!r} has size {size}")
        out[name] = size
    return out


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None):
    """Multi-host init (≙ Spark cluster bring-up). On a TPU pod slice the
    arguments are auto-detected from the environment."""
    if num_processes is not None and num_processes > 1:
        jax.distributed.initialize(coordinator_address, num_processes,
                                   process_id)


def create_mesh(axes=None, devices=None) -> Mesh:
    """Build a Mesh from {axis_name: size} or a template string
    (:func:`parse_template`, e.g. ``"dp2,tp2,pp2"``); -1 sizes one axis
    from the remaining device count."""
    devices = list(devices if devices is not None else jax.devices())
    if isinstance(axes, str):
        axes = parse_template(axes)
    axes = dict(axes or {"dp": len(devices)})
    known = 1
    wild = None
    for k, v in axes.items():
        if v == -1:
            wild = k
        else:
            known *= v
    if wild is not None:
        axes[wild] = len(devices) // known
    total = int(np.prod(list(axes.values())))
    if total > len(devices):
        raise ValueError(f"mesh {axes} needs {total} devices, "
                         f"have {len(devices)}")
    arr = np.asarray(devices[:total]).reshape(tuple(axes.values()))
    mesh = Mesh(arr, tuple(axes.keys()))
    global _current_mesh
    _current_mesh = mesh
    return mesh


def get_mesh() -> Mesh:
    global _current_mesh
    if _current_mesh is None:
        _current_mesh = create_mesh()
    return _current_mesh


def set_mesh(mesh: Mesh):
    global _current_mesh
    _current_mesh = mesh


def data_sharding(mesh: Mesh, batch_axes: Sequence[str] = ("dp",)):
    """NamedSharding placing the leading batch dim over the given axes."""
    axes = [a for a in batch_axes if a in mesh.axis_names]
    return NamedSharding(mesh, P(tuple(axes) if len(axes) > 1 else axes[0]))


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())


def virtual_devices(n: int = 8):
    """For tests: require n virtual CPU devices (set via XLA_FLAGS before
    jax import — see tests/conftest.py)."""
    devs = jax.devices()
    if len(devs) < n:
        raise RuntimeError(
            f"need {n} devices; set XLA_FLAGS=--xla_force_host_platform_"
            f"device_count={n} and JAX_PLATFORMS=cpu before importing jax")
    return devs[:n]


def shard_batch(mesh: Mesh, batch, batch_axes: Sequence[str] = ("dp",)):
    """Device-put a host batch with its leading dim sharded over dp axes —
    the analogue of one Spark partition landing on each executor."""
    sharding = data_sharding(mesh, batch_axes)
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, sharding), batch)
