"""Gradient synchronization strategies
(≙ parameters/AllReduceParameter.scala, FP16CompressedTensor.scala,
ParameterOperations.scala).

The reference implements a partitioned parameter server on the Spark block
manager: each task slices its gradient into #partitions blocks, puts them,
each partition aggregates its slice, applies the update, and workers fetch
the new weight slices (AllReduceParameter.scala:222 aggregateGradientPartition,
:273 putGradients).  FP16CompressedTensor halves the bytes on the wire.

On TPU these become XLA collectives over the mesh:

  all-reduce            -> lax.psum(grads, 'dp')            (replicated params)
  partitioned PS        -> reduce_scatter + all_gather      (FSDP, sharded
                           params/opt state — same comm volume as the
                           reference's partitioned scheme, but on ICI)
  fp16 compression      -> cast to bf16/fp16 before psum, upcast after
                           (bf16 preferred on TPU: same 16 bits, fp32 range)
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..observability import collectives as _acct
from ._compat import axis_size


def _cast(tree, dtype):
    return jax.tree_util.tree_map(
        lambda g: g.astype(dtype)
        if jnp.issubdtype(g.dtype, jnp.floating) else g, tree)


def _axis_size_or_none(axis_name):
    """Static axis size when called under shard_map/pmap tracing; None
    outside a binding context (pure-function unit tests)."""
    try:
        return axis_size(axis_name)
    except Exception:
        return None


def allreduce_gradients(grads, axis_name: str = "dp",
                        compress: Optional[str] = None, mean: bool = True):
    """Sum (or mean) gradients across the axis, optionally compressed to
    16-bit on the wire (≙ FP16CompressedTensor).  Call inside shard_map.

    Accounts the ring all-reduce volume (raw and on-the-wire bytes) to
    the active telemetry recorder at trace time — shapes are static
    here, so the numbers are exact per executed step."""
    orig_dtypes = jax.tree_util.tree_map(lambda g: g.dtype, grads)
    n = _axis_size_or_none(axis_name)
    if n is not None:
        raw = _acct.tree_bytes(grads)
        wire_item = _acct.compressed_itemsize(compress)
        wire = _acct.tree_bytes(grads, wire_itemsize=wire_item)
        _acct.account_collective(
            "allreduce", _acct.ring_allreduce_bytes(raw, n),
            _acct.ring_allreduce_bytes(wire, n))
    if compress in ("fp16", "float16"):
        grads = _cast(grads, jnp.float16)
    elif compress in ("bf16", "bfloat16"):
        grads = _cast(grads, jnp.bfloat16)
    reduced = lax.pmean(grads, axis_name) if mean else lax.psum(grads, axis_name)
    return jax.tree_util.tree_map(
        lambda g, d: g.astype(d), reduced, orig_dtypes)


def reduce_scatter_gradients(grads, axis_name: str = "dp", mean: bool = True,
                             mask=None):
    """Each shard keeps 1/N of every sharded gradient leaf (scatter dim 0)
    — the FSDP half of the partitioned parameter server.  ``mask`` (a
    params-shaped tree of bools, e.g. from :func:`shardable_mask_dim0`)
    marks which leaves are dim-0-sharded; without it, any leaf whose
    dim 0 divides the axis size is scattered.  Unsharded leaves are
    all-reduced instead.  Call inside shard_map with FULL-shape grads.

    Trace-time accounting: scattered leaves ride a reduce-scatter
    (S*(n-1)/n wire bytes), unscattered ones a full all-reduce."""
    n = axis_size(axis_name)
    rs_bytes, ar_bytes = [0], [0]

    def rs(g, s=None):
        sharded = (g.ndim > 0 and g.shape[0] % n == 0) if s is None else s
        if not sharded:
            ar_bytes[0] += _acct.leaf_bytes(g)
            return lax.pmean(g, axis_name) if mean else lax.psum(g, axis_name)
        rs_bytes[0] += _acct.leaf_bytes(g)
        out = lax.psum_scatter(g, axis_name, scatter_dimension=0,
                               tiled=True)
        return out / n if mean else out

    if mask is None:
        out = jax.tree_util.tree_map(rs, grads)
    else:
        out = jax.tree_util.tree_map(rs, grads, mask)
    if rs_bytes[0]:
        _acct.account_collective(
            "reduce_scatter", _acct.ring_gather_bytes(rs_bytes[0], n),
            _acct.ring_gather_bytes(rs_bytes[0], n))
    if ar_bytes[0]:
        _acct.account_collective(
            "allreduce", _acct.ring_allreduce_bytes(ar_bytes[0], n),
            _acct.ring_allreduce_bytes(ar_bytes[0], n))
    return out


def allgather_params(params, axis_name: str = "dp", mask=None):
    """Rebuild full parameters from dim-0 shards (the getWeights fetch).
    ``mask`` marks which leaves are actually sharded (replicated leaves
    must NOT be gathered — that would tile N copies); without a mask any
    non-scalar leaf is gathered."""
    n = _axis_size_or_none(axis_name)
    ag_bytes = [0]

    def ag(p, s=None):
        if p.ndim == 0 or (s is not None and not s):
            return p
        ag_bytes[0] += _acct.leaf_bytes(p) * (n or 1)  # full gathered size
        return lax.all_gather(p, axis_name, axis=0, tiled=True)

    if mask is None:
        out = jax.tree_util.tree_map(ag, params)
    else:
        out = jax.tree_util.tree_map(ag, params, mask)
    if ag_bytes[0] and n:
        _acct.account_collective(
            "allgather", _acct.ring_gather_bytes(ag_bytes[0], n),
            _acct.ring_gather_bytes(ag_bytes[0], n))
    return out


def shardable_mask_dim0(tree, n):
    """Bool mask over ``tree``: True where a leaf's dim 0 is divisible by
    ``n`` (those leaves get dim-0-sharded for FSDP; the rest stay
    replicated).  Computed host-side from GLOBAL shapes."""
    def mark(p):
        return p.ndim > 0 and p.shape[0] % n == 0
    return jax.tree_util.tree_map(mark, tree)
