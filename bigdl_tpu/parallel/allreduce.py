"""Gradient synchronization strategies
(≙ parameters/AllReduceParameter.scala, FP16CompressedTensor.scala,
ParameterOperations.scala).

The reference implements a partitioned parameter server on the Spark block
manager: each task slices its gradient into #partitions blocks, puts them,
each partition aggregates its slice, applies the update, and workers fetch
the new weight slices (AllReduceParameter.scala:222 aggregateGradientPartition,
:273 putGradients).  FP16CompressedTensor halves the bytes on the wire.

On TPU these become XLA collectives over the mesh:

  all-reduce            -> lax.psum(grads, 'dp')            (replicated params)
  partitioned PS        -> reduce_scatter + all_gather      (FSDP, sharded
                           params/opt state — same comm volume as the
                           reference's partitioned scheme, but on ICI)
  fp16 compression      -> cast to bf16/fp16 before psum, upcast after
                           (bf16 preferred on TPU: same 16 bits, fp32 range)
"""
from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..observability import collectives as _acct
from ._compat import axis_size

log = logging.getLogger(__name__)


def _path_str(path):
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                    for p in path)


def _report_dense_fallback(counter: str, names, op: str):
    """Sharding coverage must be observable, not silent: leaves that fall
    back to a dense per-leaf collective (dim 0 not divisible / masked
    out) bump a ``comm/*`` counter once per trace and name themselves in
    a debug log.  Runs at trace time — once per compiled program, so the
    counter reads 'how many leaves the last-built step left unsharded'
    (re-traces re-report, like the collective gauges)."""
    if not names:
        return
    from ..observability.recorder import get_recorder
    rec = get_recorder()
    if rec.enabled:
        rec.inc(counter, len(names))
    log.debug("%s dense fallback for %d leaves (dim 0 not divisible by "
              "the axis, or masked unsharded): %s", op, len(names),
              ", ".join(names))


def _cast(tree, dtype):
    return jax.tree_util.tree_map(
        lambda g: g.astype(dtype)
        if jnp.issubdtype(g.dtype, jnp.floating) else g, tree)


def _axis_size_or_none(axis_name):
    """Static axis size when called under shard_map/pmap tracing; None
    outside a binding context (pure-function unit tests)."""
    try:
        return axis_size(axis_name)
    except Exception:
        return None


def allreduce_gradients(grads, axis_name: str = "dp",
                        compress: Optional[str] = None, mean: bool = True,
                        group: Optional[str] = None):
    """Sum (or mean) gradients across the axis, optionally compressed to
    16-bit on the wire (≙ FP16CompressedTensor).  Call inside shard_map.

    Compressed means ship the 1/n-scaled gradient (pre-scaled in fp32,
    then cast): a raw 16-bit ring SUM of n shards can overflow fp16's
    65504 range, and the same mean-on-the-wire rule keeps this path
    numerically identical to the bucketed exchange
    (:class:`~bigdl_tpu.parallel.bucketer.GradBucketer`).

    Accounts the ring all-reduce volume (raw and on-the-wire bytes) to
    the active telemetry recorder at trace time — shapes are static
    here, so the numbers are exact per executed step.  ``group`` names
    the parallelism group for the ``comm/group.<axis>.*`` family
    (defaults to the axis name on a composed mesh — pass explicitly
    when ``axis_name`` is a tuple)."""
    orig_dtypes = jax.tree_util.tree_map(lambda g: g.dtype, grads)
    n = _axis_size_or_none(axis_name)
    if group is None and isinstance(axis_name, str):
        group = axis_name
    if n is not None:
        raw = _acct.tree_bytes(grads)
        wire_item = _acct.compressed_itemsize(compress)
        wire = _acct.tree_bytes(grads, wire_itemsize=wire_item)
        _acct.account_collective(
            "allreduce", _acct.ring_allreduce_bytes(raw, n),
            _acct.ring_allreduce_bytes(wire, n), group=group)
    cast_to = {"fp16": jnp.float16, "float16": jnp.float16,
               "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16}.get(compress)
    if cast_to is not None:
        if mean and n is not None:
            grads = jax.tree_util.tree_map(
                lambda g: (g.astype(jnp.float32) / n).astype(cast_to)
                if jnp.issubdtype(g.dtype, jnp.floating) else g, grads)
            reduced = lax.psum(grads, axis_name)
        else:       # mean=False keeps sum semantics; n unknown outside
            grads = _cast(grads, cast_to)      # a binding context
            reduced = lax.pmean(grads, axis_name) if mean \
                else lax.psum(grads, axis_name)
    else:
        reduced = lax.pmean(grads, axis_name) if mean \
            else lax.psum(grads, axis_name)
    return jax.tree_util.tree_map(
        lambda g, d: g.astype(d), reduced, orig_dtypes)


def reduce_scatter_gradients(grads, axis_name: str = "dp", mean: bool = True,
                             mask=None, group: Optional[str] = None):
    """Each shard keeps 1/N of every sharded gradient leaf (scatter dim 0)
    — the FSDP half of the partitioned parameter server.  ``mask`` (a
    params-shaped tree of bools, e.g. from :func:`shardable_mask_dim0`)
    marks which leaves are dim-0-sharded; without it, any leaf whose
    dim 0 divides the axis size is scattered.  Unsharded leaves are
    all-reduced instead.  Call inside shard_map with FULL-shape grads.

    Trace-time accounting: scattered leaves ride a reduce-scatter
    (S*(n-1)/n wire bytes), unscattered ones a full all-reduce."""
    n = axis_size(axis_name)
    if group is None and isinstance(axis_name, str):
        group = axis_name
    rs_bytes, ar_bytes = [0], [0]
    dense_leaves = []

    def rs(path, g, s=None):
        sharded = (g.ndim > 0 and g.shape[0] % n == 0) if s is None else s
        if not sharded:
            ar_bytes[0] += _acct.leaf_bytes(g)
            dense_leaves.append(_path_str(path))
            return lax.pmean(g, axis_name) if mean else lax.psum(g, axis_name)
        rs_bytes[0] += _acct.leaf_bytes(g)
        out = lax.psum_scatter(g, axis_name, scatter_dimension=0,
                               tiled=True)
        return out / n if mean else out

    if mask is None:
        out = jax.tree_util.tree_map_with_path(rs, grads)
    else:
        out = jax.tree_util.tree_map_with_path(rs, grads, mask)
    _report_dense_fallback("comm/unsharded_leaves", dense_leaves,
                           "reduce_scatter_gradients")
    if rs_bytes[0]:
        _acct.account_collective(
            "reduce_scatter", _acct.ring_gather_bytes(rs_bytes[0], n),
            _acct.ring_gather_bytes(rs_bytes[0], n), group=group)
    if ar_bytes[0]:
        _acct.account_collective(
            "allreduce", _acct.ring_allreduce_bytes(ar_bytes[0], n),
            _acct.ring_allreduce_bytes(ar_bytes[0], n), group=group)
    return out


def allgather_params(params, axis_name: str = "dp", mask=None,
                     group: Optional[str] = None):
    """Rebuild full parameters from dim-0 shards (the getWeights fetch).
    ``mask`` marks which leaves are actually sharded (replicated leaves
    must NOT be gathered — that would tile N copies); without a mask any
    non-scalar leaf is gathered."""
    n = _axis_size_or_none(axis_name)
    if group is None and isinstance(axis_name, str):
        group = axis_name
    ag_bytes = [0]
    skipped_leaves = []

    def ag(path, p, s=None):
        if p.ndim == 0 or (s is not None and not s):
            skipped_leaves.append(_path_str(path))
            return p
        ag_bytes[0] += _acct.leaf_bytes(p) * (n or 1)  # full gathered size
        return lax.all_gather(p, axis_name, axis=0, tiled=True)

    if mask is None:
        out = jax.tree_util.tree_map_with_path(ag, params)
    else:
        out = jax.tree_util.tree_map_with_path(ag, params, mask)
    _report_dense_fallback("comm/ungathered_leaves", skipped_leaves,
                           "allgather_params")
    if ag_bytes[0] and n:
        _acct.account_collective(
            "allgather", _acct.ring_gather_bytes(ag_bytes[0], n),
            _acct.ring_gather_bytes(ag_bytes[0], n), group=group)
    return out


def shardable_mask_dim0(tree, n):
    """Bool mask over ``tree``: True where a leaf's dim 0 is divisible by
    ``n`` (those leaves get dim-0-sharded for FSDP; the rest stay
    replicated).  Computed host-side from GLOBAL shapes."""
    def mark(p):
        return p.ndim > 0 and p.shape[0] % n == 0
    return jax.tree_util.tree_map(mark, tree)
