"""Gradient synchronization strategies
(≙ parameters/AllReduceParameter.scala, FP16CompressedTensor.scala,
ParameterOperations.scala).

The reference implements a partitioned parameter server on the Spark block
manager: each task slices its gradient into #partitions blocks, puts them,
each partition aggregates its slice, applies the update, and workers fetch
the new weight slices (AllReduceParameter.scala:222 aggregateGradientPartition,
:273 putGradients).  FP16CompressedTensor halves the bytes on the wire.

On TPU these become XLA collectives over the mesh:

  all-reduce            -> lax.psum(grads, 'dp')            (replicated params)
  partitioned PS        -> reduce_scatter + all_gather      (FSDP, sharded
                           params/opt state — same comm volume as the
                           reference's partitioned scheme, but on ICI)
  fp16 compression      -> cast to bf16/fp16 before psum, upcast after
                           (bf16 preferred on TPU: same 16 bits, fp32 range)
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _cast(tree, dtype):
    return jax.tree_util.tree_map(
        lambda g: g.astype(dtype)
        if jnp.issubdtype(g.dtype, jnp.floating) else g, tree)


def allreduce_gradients(grads, axis_name: str = "dp",
                        compress: Optional[str] = None, mean: bool = True):
    """Sum (or mean) gradients across the axis, optionally compressed to
    16-bit on the wire (≙ FP16CompressedTensor).  Call inside shard_map."""
    orig_dtypes = jax.tree_util.tree_map(lambda g: g.dtype, grads)
    if compress in ("fp16", "float16"):
        grads = _cast(grads, jnp.float16)
    elif compress in ("bf16", "bfloat16"):
        grads = _cast(grads, jnp.bfloat16)
    reduced = lax.pmean(grads, axis_name) if mean else lax.psum(grads, axis_name)
    return jax.tree_util.tree_map(
        lambda g, d: g.astype(d), reduced, orig_dtypes)


def reduce_scatter_gradients(grads, axis_name: str = "dp", mean: bool = True):
    """Each shard keeps 1/N of every gradient leaf (scatter dim 0) — the FSDP
    half of the partitioned parameter server."""
    n = lax.axis_size(axis_name)

    def rs(g):
        if g.ndim == 0 or g.shape[0] % n != 0:
            return lax.pmean(g, axis_name) if mean else lax.psum(g, axis_name)
        out = lax.psum_scatter(g, axis_name, scatter_dimension=0,
                               tiled=True)
        return out / n if mean else out

    return jax.tree_util.tree_map(rs, grads)


def allgather_params(params, axis_name: str = "dp", full_shapes=None):
    """Rebuild full parameters from dim-0 shards (the getWeights fetch)."""
    def ag(p, full_shape=None):
        if p.ndim == 0:
            return p
        return lax.all_gather(p, axis_name, axis=0, tiled=True)

    if full_shapes is None:
        return jax.tree_util.tree_map(ag, params)
    return jax.tree_util.tree_map(ag, params, full_shapes)


def shard_leaf_dim0(tree, n):
    """Host-side: split each leaf's dim 0 into n shards (leaves whose dim 0
    is not divisible stay replicated). Used to set up FSDP param layout."""
    def mark(p):
        return p.ndim > 0 and p.shape[0] % n == 0
    return jax.tree_util.tree_map(mark, tree)
