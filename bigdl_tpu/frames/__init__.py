"""ML-pipeline integration (≙ dlframes/: DLEstimator.scala,
DLClassifier.scala, DLImageReader.scala, DLImageTransformer.scala +
pyspark/bigdl/dlframes/dl_classifier.py).

The reference plugs BigDL into Spark-ML Pipelines (fit on a DataFrame of
feature/label columns, transform adds a prediction column).  There is no
Spark in a TPU pod, so the same estimator/model/transformer semantics are
exposed sklearn-style over numpy arrays / lists of dicts ("rows"):

    est = DLEstimator(model, criterion, [13], [1]).set_max_epoch(10)
    dl_model = est.fit(rows)                 # rows: (x, y) or list of dicts
    out_rows = dl_model.transform(rows)      # adds 'prediction'

DLClassifier adds argmax class prediction, DLImageReader loads image
folders into rows, DLImageTransformer applies a vision FeatureTransformer
per row — the same pipeline stages, minus the JVM.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from ..nn.module import Module
from .. import optim as O
from ..data.imageframe import ImageFeature, FeatureTransformer


Rows = Union[Sequence[Dict], tuple]


def _rows_to_arrays(data, features_col, label_col=None):
    if isinstance(data, tuple):
        x, y = data if len(data) == 2 else (data[0], None)
        return np.asarray(x), None if y is None else np.asarray(y)
    xs = [np.asarray(r[features_col]) for r in data]
    ys = None
    if label_col is not None and data and label_col in data[0]:
        ys = np.asarray([r[label_col] for r in data], np.float32)
    return np.stack(xs), ys


class _Params:
    """Shared fluent params (≙ dl_classifier.py Has* mixins)."""

    def __init__(self):
        self.batch_size = 1
        self.max_epoch = 50
        self.learning_rate = 1e-3
        self.features_col = "features"
        self.label_col = "label"
        self.prediction_col = "prediction"

    def set_batch_size(self, v):
        self.batch_size = v
        return self

    def get_batch_size(self):
        return self.batch_size

    def set_max_epoch(self, v):
        self.max_epoch = v
        return self

    def get_max_epoch(self):
        return self.max_epoch

    def set_learning_rate(self, v):
        self.learning_rate = v
        return self

    def get_learning_rate(self):
        return self.learning_rate

    def set_features_col(self, v):
        self.features_col = v
        return self

    def set_label_col(self, v):
        self.label_col = v
        return self

    def set_prediction_col(self, v):
        self.prediction_col = v
        return self


class DLEstimator(_Params):
    """Fit a model+criterion over (features, label) rows
    (≙ dlframes/DLEstimator.scala)."""

    def __init__(self, model: Module, criterion, feature_size: Sequence[int],
                 label_size: Sequence[int], optim_method=None, mesh=None):
        super().__init__()
        self.model = model
        self.criterion = criterion
        self.feature_size = tuple(feature_size)
        self.label_size = tuple(label_size)
        self.optim_method = optim_method
        self.mesh = mesh

    def fit(self, data) -> "DLModel":
        x, y = _rows_to_arrays(data, self.features_col, self.label_col)
        x = x.reshape((-1,) + self.feature_size).astype(np.float32)
        if y is None:
            raise ValueError(f"fit needs a {self.label_col!r} column")
        y = np.asarray(y, np.float32).reshape((-1,) + self.label_size)
        method = self.optim_method or O.Adam(
            learning_rate=self.learning_rate)
        if self.mesh is not None:
            from ..optim.distri_optimizer import DistriOptimizer
            opt = DistriOptimizer(self.model, (x, y), self.criterion,
                                  batch_size=self.batch_size, mesh=self.mesh)
        else:
            opt = O.LocalOptimizer(self.model, (x, y), self.criterion,
                                   batch_size=self.batch_size)
        opt.set_optim_method(method) \
           .set_end_when(O.Trigger.max_epoch(self.max_epoch))
        model = opt.optimize()
        return self._wrap_model(model)

    def _wrap_model(self, model):
        m = DLModel(model, self.feature_size)
        m.batch_size = self.batch_size
        m.features_col = self.features_col
        m.prediction_col = self.prediction_col
        return m


class DLModel(_Params):
    """Transform rows by adding a prediction column
    (≙ dlframes/DLEstimator.scala DLModel)."""

    def __init__(self, model: Module, feature_size: Sequence[int]):
        super().__init__()
        self.model = model
        self.feature_size = tuple(feature_size)

    def set_feature_size(self, v):
        self.feature_size = tuple(v)
        return self

    def get_feature_size(self):
        return self.feature_size

    def _predict(self, x: np.ndarray) -> np.ndarray:
        x = x.reshape((-1,) + self.feature_size).astype(np.float32)
        return O.Predictor(self.model, batch_size=self.batch_size) \
            .predict(x)

    def transform(self, data):
        if isinstance(data, tuple) or isinstance(data, np.ndarray):
            x = data[0] if isinstance(data, tuple) else data
            return self._predict(np.asarray(x))
        x, _ = _rows_to_arrays(data, self.features_col)
        preds = self._predict(x)
        out = []
        for r, p in zip(data, np.asarray(preds)):
            r2 = dict(r)
            r2[self.prediction_col] = p
            out.append(r2)
        return out


class DLClassifier(DLEstimator):
    """DLEstimator with scalar class labels and argmax predictions
    (≙ dlframes/DLClassifier.scala)."""

    def __init__(self, model: Module, criterion, feature_size,
                 optim_method=None, mesh=None):
        super().__init__(model, criterion, feature_size, (),
                         optim_method=optim_method, mesh=mesh)

    def _wrap_model(self, model):
        m = DLClassifierModel(model, self.feature_size)
        m.batch_size = self.batch_size
        m.features_col = self.features_col
        m.prediction_col = self.prediction_col
        return m


class DLClassifierModel(DLModel):
    """≙ dlframes/DLClassifier.scala DLClassifierModel: prediction is the
    1-based argmax class, like the reference's ClassNLL convention."""

    def _predict(self, x):
        x = x.reshape((-1,) + self.feature_size).astype(np.float32)
        return O.Predictor(self.model, batch_size=self.batch_size) \
            .predict_class(x)


class DLImageReader:
    """Read an image folder into rows of ImageFeatures
    (≙ dlframes/DLImageReader.scala readImages)."""

    @staticmethod
    def read_images(path: str, scale_to: Optional[int] = None) -> List[Dict]:
        from ..data.imageframe import ImageFrame
        frame = ImageFrame.read(path, scale_to=scale_to)
        return [{"image": f, "uri": f.get(ImageFeature.URI)}
                for f in frame]


class DLImageTransformer:
    """Apply a vision FeatureTransformer to the 'image' column
    (≙ dlframes/DLImageTransformer.scala)."""

    def __init__(self, transformer: FeatureTransformer):
        self.transformer = transformer

    def transform(self, rows: List[Dict], input_col="image",
                  output_col="output") -> List[Dict]:
        out = []
        for r in rows:
            r2 = dict(r)
            r2[output_col] = self.transformer.transform(r[input_col])
            out.append(r2)
        return out
