"""ML-pipeline integration (≙ dlframes/: DLEstimator.scala,
DLClassifier.scala, DLImageReader.scala, DLImageTransformer.scala +
pyspark/bigdl/dlframes/dl_classifier.py).

The reference plugs BigDL into Spark-ML Pipelines (fit on a DataFrame of
feature/label columns, transform adds a prediction column).  There is no
Spark in a TPU pod, so the same estimator/model/transformer semantics are
exposed sklearn-style over numpy arrays / lists of dicts ("rows"):

    est = DLEstimator(model, criterion, [13], [1]).set_max_epoch(10)
    dl_model = est.fit(rows)                 # rows: (x, y) or list of dicts
    out_rows = dl_model.transform(rows)      # adds 'prediction'

DLClassifier adds argmax class prediction, DLImageReader loads image
folders into rows, DLImageTransformer applies a vision FeatureTransformer
per row — the same pipeline stages, minus the JVM.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from ..nn.module import Module
from .. import optim as O
from ..data.imageframe import ImageFeature, FeatureTransformer


Rows = Union[Sequence[Dict], tuple]


def _rows_to_arrays(data, features_col, label_col=None):
    if isinstance(data, tuple):
        x, y = data if len(data) == 2 else (data[0], None)
        return np.asarray(x), None if y is None else np.asarray(y)
    xs = [np.asarray(r[features_col]) for r in data]
    ys = None
    if label_col is not None and data and label_col in data[0]:
        ys = np.asarray([r[label_col] for r in data], np.float32)
    return np.stack(xs), ys


class _Params:
    """Shared fluent params (≙ dl_classifier.py Has* mixins)."""

    def __init__(self):
        self.batch_size = 1
        self.max_epoch = 50
        self.learning_rate = 1e-3
        self.features_col = "features"
        self.label_col = "label"
        self.prediction_col = "prediction"

    def set_batch_size(self, v):
        self.batch_size = v
        return self

    def get_batch_size(self):
        return self.batch_size

    def set_max_epoch(self, v):
        self.max_epoch = v
        return self

    def get_max_epoch(self):
        return self.max_epoch

    def set_learning_rate(self, v):
        self.learning_rate = v
        return self

    def get_learning_rate(self):
        return self.learning_rate

    def set_features_col(self, v):
        self.features_col = v
        return self

    def set_label_col(self, v):
        self.label_col = v
        return self

    def set_prediction_col(self, v):
        self.prediction_col = v
        return self


class DLEstimator(_Params):
    """Fit a model+criterion over (features, label) rows
    (≙ dlframes/DLEstimator.scala)."""

    def __init__(self, model: Module, criterion, feature_size: Sequence[int],
                 label_size: Sequence[int], optim_method=None, mesh=None):
        super().__init__()
        self.model = model
        self.criterion = criterion
        self.feature_size = tuple(feature_size)
        self.label_size = tuple(label_size)
        self.optim_method = optim_method
        self.mesh = mesh

    def fit(self, data) -> "DLModel":
        x, y = _rows_to_arrays(data, self.features_col, self.label_col)
        x = x.reshape((-1,) + self.feature_size).astype(np.float32)
        if y is None:
            raise ValueError(f"fit needs a {self.label_col!r} column")
        y = np.asarray(y, np.float32).reshape((-1,) + self.label_size)
        method = self.optim_method or O.Adam(
            learning_rate=self.learning_rate)
        if self.mesh is not None:
            from ..optim.distri_optimizer import DistriOptimizer
            opt = DistriOptimizer(self.model, (x, y), self.criterion,
                                  batch_size=self.batch_size, mesh=self.mesh)
        else:
            opt = O.LocalOptimizer(self.model, (x, y), self.criterion,
                                   batch_size=self.batch_size)
        opt.set_optim_method(method) \
           .set_end_when(O.Trigger.max_epoch(self.max_epoch))
        model = opt.optimize()
        return self._wrap_model(model)

    def _wrap_model(self, model):
        m = DLModel(model, self.feature_size)
        m.batch_size = self.batch_size
        m.features_col = self.features_col
        m.prediction_col = self.prediction_col
        return m


class DLModel(_Params):
    """Transform rows by adding a prediction column
    (≙ dlframes/DLEstimator.scala DLModel)."""

    def __init__(self, model: Module, feature_size: Sequence[int]):
        super().__init__()
        self.model = model
        self.feature_size = tuple(feature_size)

    def set_feature_size(self, v):
        self.feature_size = tuple(v)
        return self

    def get_feature_size(self):
        return self.feature_size

    def _predict(self, x: np.ndarray) -> np.ndarray:
        x = x.reshape((-1,) + self.feature_size).astype(np.float32)
        return O.Predictor(self.model, batch_size=self.batch_size) \
            .predict(x)

    def transform(self, data):
        if isinstance(data, tuple) or isinstance(data, np.ndarray):
            x = data[0] if isinstance(data, tuple) else data
            return self._predict(np.asarray(x))
        x, _ = _rows_to_arrays(data, self.features_col)
        preds = self._predict(x)
        out = []
        for r, p in zip(data, np.asarray(preds)):
            r2 = dict(r)
            r2[self.prediction_col] = p
            out.append(r2)
        return out


class DLClassifier(DLEstimator):
    """DLEstimator with scalar class labels and argmax predictions
    (≙ dlframes/DLClassifier.scala)."""

    def __init__(self, model: Module, criterion, feature_size,
                 optim_method=None, mesh=None):
        super().__init__(model, criterion, feature_size, (),
                         optim_method=optim_method, mesh=mesh)

    def _wrap_model(self, model):
        m = DLClassifierModel(model, self.feature_size)
        m.batch_size = self.batch_size
        m.features_col = self.features_col
        m.prediction_col = self.prediction_col
        return m


class DLClassifierModel(DLModel):
    """≙ dlframes/DLClassifier.scala DLClassifierModel: prediction is the
    1-based argmax class, like the reference's ClassNLL convention."""

    def _predict(self, x):
        x = x.reshape((-1,) + self.feature_size).astype(np.float32)
        return O.Predictor(self.model, batch_size=self.batch_size) \
            .predict_class(x)


class DLImageReader:
    """Read an image folder into rows of ImageFeatures
    (≙ dlframes/DLImageReader.scala readImages)."""

    @staticmethod
    def read_images(path: str, scale_to: Optional[int] = None) -> List[Dict]:
        from ..data.imageframe import ImageFrame
        frame = ImageFrame.read(path, scale_to=scale_to)
        return [{"image": f, "uri": f.get(ImageFeature.URI)}
                for f in frame]


class DLImageTransformer:
    """Apply a vision FeatureTransformer to the 'image' column
    (≙ dlframes/DLImageTransformer.scala)."""

    def __init__(self, transformer: FeatureTransformer):
        self.transformer = transformer

    def transform(self, rows: List[Dict], input_col="image",
                  output_col="output") -> List[Dict]:
        import copy as _copy
        out = []
        for r in rows:
            r2 = dict(r)
            # vision FeatureTransformers mutate the feature in place
            # (reference semantics); transform a COPY so repeated
            # pipeline passes (fit, then transform) never re-normalize
            # the caller's rows
            r2[output_col] = self.transformer.transform(
                _copy.deepcopy(r[input_col]))
            out.append(r2)
        return out


def _hwc_to_chw(img: np.ndarray) -> np.ndarray:
    """Shared layout rule (same guards as imageframe.ImageFrameToSample):
    2D grayscale becomes (1, H, W); already-CHW passes through."""
    img = np.asarray(img, np.float32)
    if img.ndim == 2:
        img = img[None]
    elif img.ndim == 3 and img.shape[0] not in (1, 3):
        img = np.transpose(img, (2, 0, 1))
    return np.ascontiguousarray(img)


class ImageFeatureToTensor:
    """Pipeline stage turning an ImageFeature column into a CHW numpy
    'features' column ready for DLEstimator/DLClassifier (the bridge the
    reference gets from DLImageTransformer's internal MatToTensor +
    ImageFeatureToTensor, dlframes/DLImageTransformer.scala:62)."""

    def __init__(self, input_col="image", output_col="features",
                 label_col="label"):
        self.input_col = input_col
        self.output_col = output_col
        self.label_col = label_col

    def transform(self, rows: List[Dict]) -> List[Dict]:
        out = []
        for r in rows:
            feat = r[self.input_col]
            img = feat.image if isinstance(feat, ImageFeature) else feat
            r2 = dict(r)
            r2[self.output_col] = _hwc_to_chw(img)
            if isinstance(feat, ImageFeature) and feat.label is not None \
                    and self.label_col not in r2:
                r2[self.label_col] = feat.label
            out.append(r2)
        return out


class Pipeline:
    """Ordered stage composition, the Spark-ML Pipeline contract the
    reference's dlframes plug into (org.apache.spark.ml.Pipeline):
    ``fit`` walks the stages — a Transformer (has ``transform``) maps the
    rows through; an Estimator (has ``fit``) is fitted on the current
    rows and its resulting model transforms them for the stages after
    it.  The result is a :class:`PipelineModel` of transformers only.
    """

    def __init__(self, stages: Sequence):
        self.stages = list(stages)

    def fit(self, rows) -> "PipelineModel":
        fitted = []
        cur = rows
        for i, stage in enumerate(self.stages):
            if hasattr(stage, "fit"):
                model = stage.fit(cur)
                fitted.append(model)
                last = i == len(self.stages) - 1
                cur = cur if last else model.transform(cur)
            elif hasattr(stage, "transform"):
                fitted.append(stage)
                cur = stage.transform(cur)
            else:
                raise TypeError(
                    f"pipeline stage {i} ({type(stage).__name__}) has "
                    "neither fit nor transform")
        return PipelineModel(fitted)

    def transform(self, rows):
        raise TypeError("Pipeline must be fit() first; transform lives "
                        "on the returned PipelineModel")


class PipelineModel:
    """The fitted pipeline: transforms rows through every stage in
    order (org.apache.spark.ml.PipelineModel.transform)."""

    def __init__(self, stages: Sequence):
        self.stages = list(stages)

    def transform(self, rows):
        for stage in self.stages:
            rows = stage.transform(rows)
        return rows
