"""Post-training int8 quantization (≙ nn/quantized/: Linear.scala,
SpatialConvolution.scala, SpatialDilatedConvolution.scala, Quantizer.scala,
Quantizable.scala, Quantization.scala).

The reference quantizes weights offline (per-output-channel symmetric
min/max) and activations at runtime, running int8 GEMMs in MKL.  TPU-first
design: int8 weights with per-channel fp32 scales; activations quantized
per-tensor inside the jitted graph; `lax.dot_general`/`conv` with
`preferred_element_type=int32` lowers onto the MXU's int8 path (2x the
bf16 MACs on v5e).  Quantized modules are inference-only, like the
reference (`QuantizedModule` has no backward).

`quantize(model)` rewrites a model tree in place of the reference's
`Quantizer.quantize` graph rewrite: containers are walked recursively and
every Linear / SpatialConvolution with initialized weights is swapped for
its quantized twin carrying frozen int8 weights.
"""
from __future__ import annotations

import copy
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..nn.module import Module
from ..nn import containers as containers_mod
from ..nn import graph as graph_mod
from ..nn import linear as linear_mod
from ..nn import conv as conv_mod


def quantize_weights_symmetric(w: np.ndarray, axis: int = 0):
    """Per-output-channel symmetric int8 (≙ quantized/Utils.scala min/max
    thresholds; symmetric, so zero-point free — friendlier to the MXU)."""
    w = np.asarray(w, np.float32)
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
    absmax = np.maximum(np.abs(w).max(axis=reduce_axes, keepdims=True),
                        1e-8)
    scale = absmax / 127.0
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return q, scale.astype(np.float32)


def quantize_rows(x, axis: int = -1):
    """In-graph (jnp) twin of :func:`quantize_weights_symmetric`:
    symmetric int8 with a per-channel fp32 scale over ``axis``
    (keepdims, so the dequant multiply broadcasts).  Used by the paged
    KV cache's int8 option (``serving/kvcache.py``), where "channel" is
    one (head, position) row of head_dim values and the scale must be
    computed inside the jitted decode step."""
    xf = x.astype(jnp.float32)
    absmax = jnp.maximum(jnp.max(jnp.abs(xf), axis=axis, keepdims=True),
                         1e-8)
    scale = (absmax / 127.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_rows(q, scale, dtype=jnp.float32):
    """Jittable inverse of :func:`quantize_rows` (int8 × broadcast
    scale → ``dtype``)."""
    return q.astype(dtype) * scale.astype(dtype)


def _quantize_activations(x, absmax=None):
    """Per-tensor symmetric int8, computed in-graph (runtime quantization,
    ≙ quantized Linear.scala updateOutput's input quantization)."""
    if absmax is None:
        absmax = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    scale = absmax / 127.0
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


class QuantizedModule(Module):
    """Inference-only base (≙ nn/quantized/QuantizedModule.scala)."""

    def init(self, rng):
        return {}

    def backward(self, *a, **k):
        raise RuntimeError(
            f"{type(self).__name__} is inference-only (quantized)")


class QuantizedLinear(QuantizedModule):
    """int8 x int8 -> int32 GEMM with fp32 rescale
    (≙ nn/quantized/Linear.scala).

    ``act_absmax`` (from :func:`calibrate_activation_absmax`) freezes the
    activation scale: the runtime per-batch |x| reduction — a serialized
    full pass over the input before the GEMM can start — disappears, and
    the round/clip fuses into the producer's epilogue."""

    def __init__(self, weight, bias=None, act_absmax=None, name=None):
        super().__init__(name=name)
        qw, wscale = quantize_weights_symmetric(np.asarray(weight), axis=0)
        self.qweight = jnp.asarray(qw)               # (out, in) int8
        self.wscale = jnp.asarray(wscale.reshape(-1))  # (out,)
        self.bias = None if bias is None else jnp.asarray(bias, jnp.float32)
        self.act_absmax = None if act_absmax is None else float(act_absmax)

    @staticmethod
    def from_float(layer: linear_mod.Linear, params=None,
                   act_absmax=None) -> "QuantizedLinear":
        p = params if params is not None \
            else layer.ensure_initialized()[layer.name]
        return QuantizedLinear(p["weight"], p.get("bias"),
                               act_absmax=act_absmax,
                               name=f"{layer.name}_q")

    def apply(self, params, x, ctx):
        qx, xscale = _quantize_activations(x, self.act_absmax)
        acc = lax.dot_general(
            qx, self.qweight,
            (((qx.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * (xscale * self.wscale)
        if self.bias is not None:
            out = out + self.bias
        return out


class QuantizedSpatialConvolution(QuantizedModule):
    """int8 conv with int32 accumulation (≙ nn/quantized/
    SpatialConvolution.scala). NCHW like the float layer."""

    def __init__(self, weight, bias=None, stride=(1, 1), padding=(0, 0),
                 dilation=(1, 1), n_group=1, format="NCHW",
                 act_absmax=None, name=None):
        super().__init__(name=name)
        # float layer stores OIHW in both formats (only the activation
        # layout differs — see nn/conv.py SpatialConvolution.apply)
        qw, wscale = quantize_weights_symmetric(np.asarray(weight), axis=0)
        self.qweight = jnp.asarray(qw)
        self.format = format
        self._cshape = (1, -1, 1, 1) if format == "NCHW" else (1, 1, 1, -1)
        self.wscale = jnp.asarray(wscale.reshape(self._cshape))
        self.bias = None if bias is None else jnp.asarray(bias, jnp.float32)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.n_group = n_group
        self.act_absmax = None if act_absmax is None else float(act_absmax)

    @staticmethod
    def from_float(layer, params=None,
                   act_absmax=None) -> "QuantizedSpatialConvolution":
        p = params if params is not None \
            else layer.ensure_initialized()[layer.name]
        return QuantizedSpatialConvolution(
            np.asarray(p["weight"]), p.get("bias"), stride=layer.stride,
            padding=layer.pad, n_group=getattr(layer, "n_group", 1),
            format=getattr(layer, "format", "NCHW"),
            act_absmax=act_absmax,
            name=f"{layer.name}_q")

    def apply(self, params, x, ctx):
        from ..nn.conv import _same_pad
        qx, xscale = _quantize_activations(x, self.act_absmax)
        spatial = x.shape[2:4] if self.format == "NCHW" else x.shape[1:3]
        ksize = self.qweight.shape[2:4]
        # per-axis: -1 selects SAME on that axis only (mirrors the float
        # layer's SpatialConvolution._padding)
        pad = tuple(
            _same_pad(spatial[i], self.stride[i], ksize[i], self.dilation[i])
            if p == -1 else (p, p)
            for i, p in enumerate(self.padding))
        dn = ("NCHW", "OIHW", "NCHW") if self.format == "NCHW" \
            else ("NHWC", "OIHW", "NHWC")
        acc = lax.conv_general_dilated(
            qx.astype(jnp.int8), self.qweight,
            window_strides=self.stride,
            padding=pad,
            rhs_dilation=self.dilation,
            dimension_numbers=dn,
            feature_group_count=self.n_group,
            preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * (xscale * self.wscale)
        if self.bias is not None:
            out = out + self.bias.reshape(self._cshape)
        return out


class QuantizedSpatialDilatedConvolution(QuantizedSpatialConvolution):
    """int8 dilated conv with int32 accumulation
    (≙ nn/quantized/SpatialDilatedConvolution.scala:30): same MXU int8
    path as the plain conv with rhs_dilation set."""

    @staticmethod
    def from_float(layer, params=None, act_absmax=None) \
            -> "QuantizedSpatialDilatedConvolution":
        p = params if params is not None \
            else layer.ensure_initialized()[layer.name]
        return QuantizedSpatialDilatedConvolution(
            np.asarray(p["weight"]), p.get("bias"), stride=layer.stride,
            padding=layer.pad, dilation=layer.dilation,
            act_absmax=act_absmax,
            name=f"{layer.name}_q")


_QUANTIZABLE = {}


def _register_defaults():
    _QUANTIZABLE[linear_mod.Linear] = QuantizedLinear.from_float
    _QUANTIZABLE[conv_mod.SpatialConvolution] = \
        QuantizedSpatialConvolution.from_float
    _QUANTIZABLE[conv_mod.SpatialDilatedConvolution] = \
        QuantizedSpatialDilatedConvolution.from_float


_register_defaults()


def calibrate_activation_absmax(model: Module, batches, params=None,
                                state=None):
    """Per-quantizable-layer input |x| maxima over ``batches`` (a list or
    iterable of model input arrays), collected in ONE jitted forward per
    batch via the ctx state side channel.

    Why: runtime activation quantization puts a full-tensor reduction in
    front of every int8 GEMM/conv — a serialized extra pass over the
    activations that makes the int8 path HBM-bound.  Static calibrated
    scales remove it (the standard post-training-quantization recipe;
    the reference's runtime quantization is the MKL-era equivalent,
    nn/quantized/Linear.scala updateOutput).

    Caveat (standard PTQ): maxima are measured on the FLOAT model's
    inputs; once upstream layers are quantized the real activations
    drift slightly, and any runtime value beyond the baked absmax is
    clipped silently.  A 2% headroom factor is applied to soften this;
    calibrate with representative data."""
    params = params if params is not None else model.ensure_initialized()
    state = state if state is not None else dict(model._state or {})
    targets = [m for m in model.modules() if type(m) in _QUANTIZABLE]
    origs = []
    for m in targets:
        orig = m.apply

        def wrapped(p, x, ctx, _m=m, _orig=orig):
            cur = jnp.max(jnp.abs(x.astype(jnp.float32)))
            key = "__calib__" + _m.name
            prev = ctx.new_state.get(key)
            ctx.new_state[key] = cur if prev is None \
                else jnp.maximum(prev, cur)
            return _orig(p, x, ctx)

        m.apply = wrapped
        origs.append((m, orig))
    try:
        run = jax.jit(lambda p, s, x: model.run(p, x, state=s,
                                                training=False)[1])
        out = {}
        for x in batches:
            st = run(params, state, jnp.asarray(x))
            for m in targets:
                v = st.get("__calib__" + m.name)
                if v is not None:
                    # same floor as the runtime path: an all-zero input
                    # (dead ReLU / gated branch) must not bake scale 0.
                    # 1.02x headroom absorbs small activation drift once
                    # upstream layers are themselves quantized
                    out[m.name] = max(out.get(m.name, 0.0),
                                      1.02 * float(v), 1e-8)
    finally:
        for m, _ in origs:
            try:
                del m.apply          # drop the instance shadow
            except AttributeError:
                pass
    return out


def quantize(model: Module, calibration_data=None) -> Module:
    """Deep-copy `model` with every quantizable layer replaced
    (≙ nn/quantized/Quantizer.scala quantize).  The trained weights live in
    the model's flat params tree keyed by module name, so the tree is
    threaded down and sliced by child name.  Non-quantized children KEEP
    their trained params and state (the reference Quantizer preserves
    them too): only the entries of replaced children are dropped from the
    carried tree — the quantized twins own frozen int8 weights instead.

    ``calibration_data`` (iterable of input batches) bakes static
    activation scales into the quantized twins via
    :func:`calibrate_activation_absmax`; without it activations are
    quantized at runtime per batch (reference behavior)."""
    params = model.ensure_initialized()
    state = dict(model._state or {})
    absmax = {}
    if calibration_data is not None:
        absmax = calibrate_activation_absmax(model, calibration_data,
                                             params=params, state=state)
    replaced: list = []
    new_model = _rewrite(model, params, replaced, absmax)
    if isinstance(new_model, (containers_mod.Container, graph_mod.Graph)):
        dropped = set(replaced)
        new_model._params = {k: v for k, v in params.items()
                             if k not in dropped}
        new_model._state = {k: v for k, v in state.items()
                            if k not in dropped}
    return new_model


def _rewrite(module: Module, params, replaced, absmax=None) -> Module:
    absmax = absmax or {}
    fn = _QUANTIZABLE.get(type(module))
    if fn is not None:
        replaced.append(module.name)
        return fn(module, params.get(module.name),
                  act_absmax=absmax.get(module.name))
    if isinstance(module, containers_mod.Container):
        clone = copy.copy(module)
        clone._children = [_rewrite(c, params, replaced, absmax)
                           for c in module.children()]
        # the top-level clone gets the carried trained tree in quantize();
        # intermediate clones must not cache stale float params
        clone._params = None
        clone._state = {}
        return clone
    if isinstance(module, graph_mod.Graph):
        # rebuild the node DAG with rewritten modules (same wiring)
        mapping = {}
        for node in module._topo:
            new_mod = None if node.module is None \
                else _rewrite(node.module, params, replaced, absmax)
            mapping[id(node)] = graph_mod.Node(
                new_mod, [mapping[id(p)] for p in node.prev_nodes])
        clone = copy.copy(module)
        clone.input_nodes = [mapping[id(n)] for n in module.input_nodes]
        clone.output_nodes = [mapping[id(n)] for n in module.output_nodes]
        clone._topo = clone._topsort()
        clone._params = None
        clone._state = {}
        return clone
    return module


def quantize_for_serving(model: Module, calibration_data=None) -> Module:
    """:func:`quantize` packaged for the serving registry
    (``bigdl_tpu.serving.ModelRegistry.register(quantize_int8=True)``):
    the rewritten model comes back eval-mode and initialized, ready to
    snapshot.  Remember the contract the registry enforces: the int8
    weights are compile-time constants inside each bucket executable,
    so updating them means re-quantize + re-register + re-warm, not a
    hot swap."""
    q = quantize(model, calibration_data=calibration_data)
    q.evaluate()
    q.ensure_initialized()
    return q


# --------------------------------------------------------------------- #
# weight-only int8 (LLM serving)                                         #
# --------------------------------------------------------------------- #
def _is_wq8(v):
    # detect by KEY SET, not a marker value: under jit the tree's leaves
    # (including any marker) become tracers, so value checks would fail
    # inside a params_transform traced into the serving program
    return isinstance(v, dict) and set(v) == {"q8", "q8_scale"}


def quantize_weights_only(params, min_size=4096):
    """Weight-only int8 for big-model serving: every float matrix leaf
    with >= ``min_size`` elements becomes ``{"q8": int8, "q8_scale":
    per-output-channel fp32 scale}`` (the key set ``_is_wq8`` /
    :func:`dequantize_weights` / :func:`quantized_bytes` detect); small
    leaves (biases, norms) stay float.  Activations are untouched — on
    TPU the decode phase is weight-STREAMING bound, so halving weight
    bytes in HBM is the win,
    and XLA fuses the int8->bf16 upconvert into the consuming matmul's
    operand read.

    The reference's int8 path (nn/quantized/) covers Linear/Conv
    modules; this params-level transform reaches models built from raw
    matmul weights (the TransformerLM flagship's wq/wk/wv/wo, w1/w3/w2,
    embeddings, head) without forking their module classes.  Pair with
    :func:`dequantize_weights` inside the jitted serving step.
    """
    def leaf(arr):
        if _is_wq8(arr):            # idempotent on already-quantized trees
            return arr
        a = np.asarray(arr)
        if (a.ndim != 2 or a.size < min_size
                or not np.issubdtype(a.dtype, np.floating)):
            return arr
        # this codebase's matmul weights are (in, out) used as x @ w
        # (transformer wq/w1/head): per-OUTPUT-channel means the LAST
        # axis; the keepdims scale broadcasts in the dequant multiply
        q, scale = quantize_weights_symmetric(a, axis=a.ndim - 1)
        return {"q8": jnp.asarray(q), "q8_scale": jnp.asarray(scale)}

    return jax.tree_util.tree_map(leaf, params, is_leaf=_is_wq8)


def dequantize_weights(qparams, dtype=jnp.bfloat16):
    """Jittable inverse of :func:`quantize_weights_only`: int8 leaves
    reconstruct as ``dtype`` (call INSIDE the jitted step so the
    upconvert fuses into the consumers instead of materializing fp
    copies in HBM)."""
    def leaf(v):
        if _is_wq8(v):
            return v["q8"].astype(dtype) * v["q8_scale"].astype(dtype)
        return v

    return jax.tree_util.tree_map(leaf, qparams, is_leaf=_is_wq8)


def quantized_bytes(qparams):
    """Total parameter bytes of a (possibly weight-only-quantized) tree
    — the HBM-resident weight footprint a serving config pays."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(
            qparams, is_leaf=_is_wq8):
        if _is_wq8(leaf):
            total += leaf["q8"].size * 1 + leaf["q8_scale"].size * 4
        else:
            a = np.asarray(leaf)
            total += a.size * a.dtype.itemsize
    return total
