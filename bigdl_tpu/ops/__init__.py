"""Hand-tuned TPU kernels (Pallas) and their XLA fallbacks.

The reference gets its hot-loop speed from Intel MKL primitives
(spark/dl ... tensor/TensorNumeric + the mkl native wrappers); on TPU the
equivalent role is played by Pallas kernels feeding the MXU, with pure-XLA
blockwise fallbacks so every op also runs (and is differentiable) on CPU.
"""
# keep a non-shadowed module alias: the next line rebinds the package
# attribute `flash_attention` to the *function*, so consumers that need
# module internals (_Config, _pallas_ok, _INTERPRET) import this alias
from . import flash_attention as flash_attention_mod  # noqa: F401
from .flash_attention import flash_attention, attention_reference

__all__ = ["flash_attention", "attention_reference", "flash_attention_mod"]
