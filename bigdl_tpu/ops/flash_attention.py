"""Flash attention: Pallas TPU kernel + blockwise-XLA fallback.

The reference framework has no fused attention (its RNN era predates it);
this kernel is the core primitive of our long-context flagship
(models/transformer.py) and of ring attention (parallel/ring_attention.py).

Design:
  * forward — Pallas kernel on TPU: grid over (batch*heads, q blocks),
    online-softmax ``fori_loop`` over key blocks held in VMEM; scores and
    accumulators in fp32 on the MXU, inputs may be bf16.
  * forward fallback — same blockwise math as a ``lax.scan`` over key
    blocks (O(seq * block) memory); used on CPU and for shapes the kernel
    does not tile.
  * backward — blockwise ``lax.scan`` recomputation from the saved
    (q, k, v, out, lse) residuals: flash-style O(seq * block) memory, no
    materialised (seq, seq) attention matrix; XLA fuses the elementwise
    neighbourhood of each block matmul.

Both paths share masking logic: a key is attended iff
``k_pos < kv_len  and  (not causal or q_pos >= k_pos)`` where the position
vectors are *global* token indices — ring attention passes shifted
positions for its rotating key/value chunks.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

try:  # Pallas is TPU-only at runtime; import lazily-guarded for CPU tests
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

# Finite "minus infinity": keeps exp()/max() NaN-free for fully-masked rows
# (the same trick as jax.nn and the original flash kernels).
DEFAULT_MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)

# Test hook: when True, Pallas kernels run in interpret mode so the TPU
# code path itself (not the XLA fallback) is exercised on CPU.
_INTERPRET = False


class _Config(NamedTuple):
    causal: bool
    sm_scale: float
    block_q: int
    block_k: int
    use_pallas: bool


# --------------------------------------------------------------------- #
# reference (quadratic) — used by tests and tiny shapes                 #
# --------------------------------------------------------------------- #
def attention_reference(q, k, v, causal: bool = False,
                        sm_scale: Optional[float] = None):
    """Naive softmax(q k^T) v with optional causal mask. (B, H, S, D)."""
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask, s, DEFAULT_MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)
                      ).astype(q.dtype)


# --------------------------------------------------------------------- #
# shared blockwise math                                                 #
# --------------------------------------------------------------------- #
def _mask(q_pos, k_pos, kv_len, causal):
    """(Sq, Sk) bool attend-mask from global positions."""
    valid = (k_pos < kv_len)[None, :]
    if causal:
        valid = valid & (q_pos[:, None] >= k_pos[None, :])
    return valid


def chunk_merge(q, k_chunk, v_chunk, acc, m, l, q_pos, k_pos, kv_len,
                sm_scale, causal):
    """Merge one key/value chunk into the online-softmax accumulators.

    q: (..., Sq, D); k_chunk/v_chunk: (..., Sk, D); acc: (..., Sq, D) fp32;
    m, l: (..., Sq) fp32 running max / normaliser. Returns updated
    (acc, m, l). This is the single primitive both the scan fallback and
    ring attention are built from.
    """
    s = jnp.einsum("...qd,...kd->...qk", q.astype(jnp.float32),
                   k_chunk.astype(jnp.float32)) * sm_scale
    s = jnp.where(_mask(q_pos, k_pos, kv_len, causal), s, DEFAULT_MASK_VALUE)
    m_new = jnp.maximum(m, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = corr * l + p.sum(axis=-1)
    acc_new = corr[..., None] * acc + jnp.einsum(
        "...qk,...kd->...qd", p, v_chunk.astype(jnp.float32))
    return acc_new, m_new, l_new


def chunk_merge_blockwise(q, k_chunk, v_chunk, acc, m, l, q_pos, k_pos,
                          kv_len, sm_scale, causal, block_k=1024):
    """chunk_merge with the kv chunk processed in ``block_k`` sub-blocks:
    same online-softmax result, but peak score memory is
    (..., Sq, block_k) instead of (..., Sq, Sk) — the memory lever for
    ring attention over long local chunks."""
    sk = k_chunk.shape[-2]
    if sk <= block_k:
        return chunk_merge(q, k_chunk, v_chunk, acc, m, l, q_pos, k_pos,
                           kv_len, sm_scale, causal)
    nb = -(-sk // block_k)
    pad = nb * block_k - sk
    if pad:   # pad keys out past kv_len so the position mask drops them
        widths = [(0, 0)] * (k_chunk.ndim - 2) + [(0, pad), (0, 0)]
        k_chunk = jnp.pad(k_chunk, widths)
        v_chunk = jnp.pad(v_chunk, widths)
        k_pos = jnp.concatenate(
            [k_pos, jnp.full((pad,), kv_len, k_pos.dtype)])
    kb = jnp.moveaxis(
        k_chunk.reshape(k_chunk.shape[:-2] + (nb, block_k)
                        + k_chunk.shape[-1:]), -3, 0)
    vb = jnp.moveaxis(
        v_chunk.reshape(v_chunk.shape[:-2] + (nb, block_k)
                        + v_chunk.shape[-1:]), -3, 0)
    kp = k_pos.reshape(nb, block_k)

    def step(carry, blk):
        acc, m, l = carry
        k_b, v_b, kp_b = blk
        return chunk_merge(q, k_b, v_b, acc, m, l, q_pos, kp_b, kv_len,
                           sm_scale, causal), None

    (acc, m, l), _ = lax.scan(step, (acc, m, l), (kb, vb, kp))
    return acc, m, l


def finalize(acc, m, l):
    """(out, lse) from final accumulators; fully-masked rows yield 0."""
    safe_l = jnp.where(l == 0.0, 1.0, l)
    out = acc / safe_l[..., None]
    lse = m + jnp.log(safe_l)
    return out, lse


def _fwd_blockwise(q, k, v, cfg: _Config):
    """lax.scan over key blocks. (B, H, S, D) -> (out, lse)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bk = min(cfg.block_k, sk)
    n_blocks = -(-sk // bk)
    pad = n_blocks * bk - sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    # (n_blocks, B, H, bk, D) so scan walks the leading axis
    kb = jnp.moveaxis(k.reshape(b, h, n_blocks, bk, d), 2, 0)
    vb = jnp.moveaxis(v.reshape(b, h, n_blocks, bk, d), 2, 0)
    q_pos = jnp.arange(sq)

    def step(carry, blk):
        acc, m, l = carry
        k_c, v_c, j = blk

        def merge(carry):
            acc, m, l = carry
            k_pos = j * bk + jnp.arange(bk)
            return chunk_merge(q, k_c, v_c, acc, m, l, q_pos, k_pos,
                               sk, cfg.sm_scale, cfg.causal)

        if cfg.causal:
            # skip blocks entirely beyond the causal horizon (matters for
            # cross/decode attention where seq_k > seq_q)
            carry = lax.cond(j * bk > sq - 1, lambda c: c, merge, carry)
        else:
            carry = merge(carry)
        return carry, None

    init = (jnp.zeros((b, h, sq, d), jnp.float32),
            jnp.full((b, h, sq), DEFAULT_MASK_VALUE, jnp.float32),
            jnp.zeros((b, h, sq), jnp.float32))
    (acc, m, l), _ = lax.scan(step, init, (kb, vb, jnp.arange(n_blocks)))
    out, lse = finalize(acc, m, l)
    return out.astype(q.dtype), lse


# --------------------------------------------------------------------- #
# Pallas kernels                                                        #
# --------------------------------------------------------------------- #
def _block_causal_mask(qi, j, block_q, block_k):
    """(block_q, block_k) bool mask for q block `qi` vs kv block `j`."""
    qp = qi * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    kp = j * block_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return qp >= kp


def _causal_hi(qi, block_q, block_k, n_kb):
    """First kv-block index past the causal horizon of q block `qi`."""
    hi = lax.div(qi * block_q + block_q - 1, block_k) + 1
    return jnp.minimum(hi, n_kb)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                sm_scale, causal, block_q, block_k, seq_k):
    # m/l/lse are carried as (bq, 1) rather than (bq,): Mosaic tiles the
    # last two dims onto (sublane, lane), and a trailing singleton keeps
    # every ref block shape legal on hardware (interpret mode never checks
    # this — the r2 kernel only failed when first run on a real TPU).
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale          # (bq, d)
    d = q.shape[-1]
    n_kb = seq_k // block_k
    hi = _causal_hi(qi, block_q, block_k, n_kb) if causal else n_kb

    def body(j, carry):
        acc, m, l = carry
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32)  # (bq, bk)
        if causal:
            s = jnp.where(_block_causal_mask(qi, j, block_q, block_k),
                          s, DEFAULT_MASK_VALUE)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))     # (bq, 1)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = corr * l + p.sum(axis=-1, keepdims=True)
        acc_new = corr * acc + jnp.dot(
            p, vb, preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    init = (jnp.zeros((block_q, d), jnp.float32),
            jnp.full((block_q, 1), DEFAULT_MASK_VALUE, jnp.float32),
            jnp.zeros((block_q, 1), jnp.float32))
    acc, m, l = lax.fori_loop(0, hi, body, init)
    safe_l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / safe_l).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(safe_l)).astype(lse_ref.dtype)


def _fwd_pallas(q, k, v, cfg: _Config):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, bk = cfg.block_q, cfg.block_k
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    grid = (b * h, sq // bq)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=cfg.sm_scale, causal=cfg.causal,
        block_q=bq, block_k=bk, seq_k=sk)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
            # trailing singleton = lane-legal block (see _fwd_kernel note)
            pl.BlockSpec((1, bq, 1), lambda bh, i: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d), lse.reshape(b, h, sq)


# --------------------------------------------------------------------- #
# Pallas backward kernels                                               #
# --------------------------------------------------------------------- #
def _bwd_kernel_dkv(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, sm_scale, causal, block_q, block_k,
                    seq_q):
    """One (batch*head, kv-block) program: accumulate dk/dv over q blocks.

    Flash-attention backward recomputes p = exp(s - lse) per block from the
    saved lse — no (seq, seq) matrix is ever materialised.
    """
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)                      # (bk, d)
    v = v_ref[0].astype(jnp.float32)
    n_qb = seq_q // block_q
    # under causality, q blocks strictly before this kv block see none of it
    lo = lax.div(ki * block_k, block_q) if causal else 0

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        dob = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lseb = lse_ref[0, pl.ds(i * block_q, block_q), :]      # (bq, 1)
        deltab = delta_ref[0, pl.ds(i * block_q, block_q), :]
        s = jnp.dot(qb, k.T, preferred_element_type=jnp.float32) * sm_scale
        p = jnp.exp(s - lseb)                             # (bq, bk)
        if causal:
            p = jnp.where(_block_causal_mask(i, ki, block_q, block_k),
                          p, 0.0)
        dv = dv + jnp.dot(p.T, dob, preferred_element_type=jnp.float32)
        dp = jnp.dot(dob, v.T, preferred_element_type=jnp.float32)
        ds_ = p * (dp - deltab) * sm_scale
        dk = dk + jnp.dot(ds_.T, qb, preferred_element_type=jnp.float32)
        return dk, dv

    d = k.shape[-1]
    init = (jnp.zeros((block_k, d), jnp.float32),
            jnp.zeros((block_k, d), jnp.float32))
    dk, dv = lax.fori_loop(lo, n_qb, body, init)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_kernel_dq(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, *, sm_scale, causal, block_q, block_k, seq_k):
    """One (batch*head, q-block) program: accumulate dq over kv blocks."""
    qi = pl.program_id(1)
    qb = q_ref[0].astype(jnp.float32)                     # (bq, d)
    dob = do_ref[0].astype(jnp.float32)
    lseb = lse_ref[0]                                     # (bq, 1)
    deltab = delta_ref[0]
    n_kb = seq_k // block_k
    hi = _causal_hi(qi, block_q, block_k, n_kb) if causal else n_kb

    def body(j, dq):
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32) * sm_scale
        p = jnp.exp(s - lseb)
        if causal:
            p = jnp.where(_block_causal_mask(qi, j, block_q, block_k),
                          p, 0.0)
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
        ds_ = p * (dp - deltab) * sm_scale
        return dq + jnp.dot(ds_, kb, preferred_element_type=jnp.float32)

    d = qb.shape[-1]
    dq = lax.fori_loop(0, hi, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_pallas(q, k, v, out, lse, do, cfg: _Config):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, bk = cfg.block_q, cfg.block_k
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    dof = do.reshape(b * h, sq, d)
    lsef = lse.reshape(b * h, sq, 1)
    # delta_i = sum_d do_i * out_i; tiny elementwise reduce, leave it to XLA
    delta = (do.astype(jnp.float32) * out.astype(jnp.float32)
             ).sum(-1).reshape(b * h, sq, 1)

    kv_kernel = functools.partial(
        _bwd_kernel_dkv, sm_scale=cfg.sm_scale, causal=cfg.causal,
        block_q=bq, block_k=bk, seq_q=sq)
    dk, dv = pl.pallas_call(
        kv_kernel,
        grid=(b * h, sk // bk),
        in_specs=[
            pl.BlockSpec((1, sq, d), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, sq, d), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, sq, 1), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, sq, 1), lambda bh, j: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        interpret=_INTERPRET,
    )(qf, kf, vf, dof, lsef, delta)

    q_kernel = functools.partial(
        _bwd_kernel_dq, sm_scale=cfg.sm_scale, causal=cfg.causal,
        block_q=bq, block_k=bk, seq_k=sk)
    dq = pl.pallas_call(
        q_kernel,
        grid=(b * h, sq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, i: (bh, i, 0)),
        ],
        out_specs=[pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0))],
        out_shape=[jax.ShapeDtypeStruct((b * h, sq, d), q.dtype)],
        interpret=_INTERPRET,
    )(qf, kf, vf, dof, lsef, delta)[0]

    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


def _pallas_ok(q, k, cfg: _Config) -> bool:
    if not (cfg.use_pallas and _HAS_PALLAS):
        return False
    sq, d = q.shape[2], q.shape[3]
    sk = k.shape[2]
    return (sq % cfg.block_q == 0 and sk % cfg.block_k == 0
            and d % 128 == 0
            and (jax.default_backend() == "tpu" or _INTERPRET))


# --------------------------------------------------------------------- #
# custom VJP                                                            #
# --------------------------------------------------------------------- #
@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(cfg: _Config, q, k, v):
    out, _ = _flash_fwd(cfg, q, k, v)
    return out


def _flash_fwd(cfg, q, k, v):
    if _pallas_ok(q, k, cfg):
        out, lse = _fwd_pallas(q, k, v, cfg)
    else:
        out, lse = _fwd_blockwise(q, k, v, cfg)
    return out, (q, k, v, out, lse)


def _flash_bwd(cfg, res, do):
    q, k, v, out, lse = res
    if _pallas_ok(q, k, cfg):
        return _bwd_pallas(q, k, v, out, lse, do, cfg)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bk = min(cfg.block_k, sk)
    n_blocks = -(-sk // bk)
    pad = n_blocks * bk - sk
    kp_ = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0))) if pad else k
    vp_ = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0))) if pad else v
    kb = jnp.moveaxis(kp_.reshape(b, h, n_blocks, bk, d), 2, 0)
    vb = jnp.moveaxis(vp_.reshape(b, h, n_blocks, bk, d), 2, 0)

    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = (dof * out.astype(jnp.float32)).sum(-1)       # (B,H,Sq)
    q_pos = jnp.arange(sq)

    def step(dq, blk):
        k_c, v_c, j = blk
        k_pos = j * bk + jnp.arange(bk)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_c.astype(jnp.float32)
                       ) * cfg.sm_scale
        msk = _mask(q_pos, k_pos, sk, cfg.causal)
        p = jnp.where(msk, jnp.exp(s - lse[..., None]), 0.0)
        dv_j = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
        dp = jnp.einsum("bhqd,bhkd->bhqk", dof, v_c.astype(jnp.float32))
        ds = p * (dp - delta[..., None]) * cfg.sm_scale
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, k_c.astype(jnp.float32))
        dk_j = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        return dq, (dk_j, dv_j)

    dq0 = jnp.zeros((b, h, sq, d), jnp.float32)
    dq, (dk_b, dv_b) = lax.scan(step, dq0, (kb, vb, jnp.arange(n_blocks)))
    dk = jnp.moveaxis(dk_b, 0, 2).reshape(b, h, n_blocks * bk, d)[:, :, :sk]
    dv = jnp.moveaxis(dv_b, 0, 2).reshape(b, h, n_blocks * bk, d)[:, :, :sk]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    use_pallas: bool = True):
    """Fused attention. q, k, v: (batch, heads, seq, head_dim).

    Pallas kernel on TPU (falls back to a blockwise lax.scan elsewhere);
    memory-efficient blockwise backward either way.
    """
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    cfg = _Config(bool(causal), float(sm_scale), int(block_q), int(block_k),
                  bool(use_pallas))
    return _flash(cfg, q, k, v)
