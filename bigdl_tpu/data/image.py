"""Image pipeline (≙ dataset/image/*.scala: Types, BytesToBGRImg,
BGRImgCropper, BGRImgRdmCropper, BGRImgNormalizer, BGRImgPixelNormalizer,
HFlip, ColorJitter, Lighting, GreyImg*, BGRImgToSample, BGRImgToBatch,
LocalImgReader).

All host-side numpy: augmentation runs on CPU workers while the TPU computes
the previous step; `*ToBatch` emits contiguous NCHW float32 MiniBatches ready
for a single host->device transfer.  Images are float32 HWC in [0, 255]
(BGR order like the reference's OpenCV path) until `*ToSample` converts to
CHW (optionally RGB) at the pipeline tail.
"""
from __future__ import annotations

import os
from typing import Iterable, Iterator, List, Optional, Sequence

import numpy as np

from .dataset import Transformer, SampleToMiniBatch
from .minibatch import MiniBatch, Sample


class LabeledBGRImage:
    """HWC float32 BGR image + 1-based float label (≙ image/Types.scala)."""

    def __init__(self, data: np.ndarray, label: float = 0.0):
        self.data = np.ascontiguousarray(data, dtype=np.float32)
        self.label = float(label)

    @property
    def height(self):
        return self.data.shape[0]

    @property
    def width(self):
        return self.data.shape[1]

    def copy(self):
        return LabeledBGRImage(self.data.copy(), self.label)


class LabeledGreyImage:
    """HW float32 grey image + label (≙ image/Types.scala GreyImage)."""

    def __init__(self, data: np.ndarray, label: float = 0.0):
        self.data = np.ascontiguousarray(data, dtype=np.float32)
        if self.data.ndim == 3 and self.data.shape[-1] == 1:
            self.data = self.data[..., 0]
        self.label = float(label)

    @property
    def height(self):
        return self.data.shape[0]

    @property
    def width(self):
        return self.data.shape[1]


# --------------------------------------------------------------------- #
# decoding                                                              #
# --------------------------------------------------------------------- #
class BytesToBGRImg(Transformer):
    """(bytes|uint8 HWC array, label) -> LabeledBGRImage
    (≙ image/BytesToBGRImg.scala)."""

    def __init__(self, normalize: float = 1.0):
        self.normalize = normalize

    def _decode(self, raw):
        if isinstance(raw, np.ndarray):
            arr = raw
        else:
            from PIL import Image
            import io
            arr = np.asarray(Image.open(io.BytesIO(raw)).convert("RGB"))
            arr = arr[..., ::-1]  # RGB -> BGR, matching the OpenCV reference
        return arr.astype(np.float32) / self.normalize

    def apply_iter(self, it):
        for item in it:
            raw, label = item if isinstance(item, tuple) else (item, 0.0)
            yield LabeledBGRImage(self._decode(raw), label)


class BytesToGreyImg(Transformer):
    """(bytes|uint8 HW array, label) -> LabeledGreyImage
    (≙ image/BytesToGreyImg.scala)."""

    def __init__(self, normalize: float = 1.0):
        self.normalize = normalize

    def apply_iter(self, it):
        for item in it:
            raw, label = item if isinstance(item, tuple) else (item, 0.0)
            if not isinstance(raw, np.ndarray):
                from PIL import Image
                import io
                raw = np.asarray(Image.open(io.BytesIO(raw)).convert("L"))
            yield LabeledGreyImage(raw.astype(np.float32) / self.normalize,
                                   label)


class LocalImgReader(Transformer):
    """(path, label) -> LabeledBGRImage, resizing the short edge to `scale_to`
    (≙ image/LocalImgReader.scala)."""

    def __init__(self, scale_to: int = 256):
        self.scale_to = scale_to

    def apply_iter(self, it):
        from PIL import Image
        for item in it:
            path, label = item if isinstance(item, tuple) else (item, 0.0)
            img = Image.open(path).convert("RGB")
            w, h = img.size
            if self.scale_to:
                if w < h:
                    nw, nh = self.scale_to, int(h * self.scale_to / w)
                else:
                    nw, nh = int(w * self.scale_to / h), self.scale_to
                img = img.resize((nw, nh), Image.BILINEAR)
            arr = np.asarray(img)[..., ::-1].astype(np.float32)
            yield LabeledBGRImage(arr, label)


def local_image_paths(root: str) -> List[tuple]:
    """Scan a class-per-subdir image folder into (path, 1-based label)
    (≙ image/LocalImageFiles.scala)."""
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    out = []
    for ci, cname in enumerate(classes):
        cdir = os.path.join(root, cname)
        for f in sorted(os.listdir(cdir)):
            if f.lower().endswith((".jpg", ".jpeg", ".png", ".bmp")):
                out.append((os.path.join(cdir, f), float(ci + 1)))
    return out


# --------------------------------------------------------------------- #
# crops / flips                                                         #
# --------------------------------------------------------------------- #
def _crop(data: np.ndarray, ch: int, cw: int, method: str, rng) -> np.ndarray:
    h, w = data.shape[:2]
    if method == "center":
        y0, x0 = (h - ch) // 2, (w - cw) // 2
    else:
        y0 = int(rng.randint(0, h - ch + 1))
        x0 = int(rng.randint(0, w - cw + 1))
    return data[y0:y0 + ch, x0:x0 + cw]


class BGRImgCropper(Transformer):
    """Crop to (crop_height, crop_width); 'random' while training, 'center'
    for eval (≙ image/BGRImgCropper.scala)."""

    def __init__(self, crop_width: int, crop_height: int,
                 crop_method: str = "random", seed: int = 0):
        self.cw, self.ch = crop_width, crop_height
        self.method = crop_method
        self._rng = np.random.RandomState(seed)

    def apply_iter(self, it):
        for img in it:
            img.data = np.ascontiguousarray(
                _crop(img.data, self.ch, self.cw, self.method, self._rng))
            yield img


class GreyImgCropper(BGRImgCropper):
    """≙ image/GreyImgCropper.scala."""


class BGRImgRdmCropper(Transformer):
    """Zero-pad `padding` on each side then random-crop back to size
    (the CIFAR augmentation; ≙ image/BGRImgRdmCropper.scala)."""

    def __init__(self, crop_width: int, crop_height: int, padding: int,
                 seed: int = 0):
        self.cw, self.ch = crop_width, crop_height
        self.padding = padding
        self._rng = np.random.RandomState(seed)

    def apply_iter(self, it):
        p = self.padding
        for img in it:
            padded = np.pad(img.data, ((p, p), (p, p), (0, 0)))
            img.data = np.ascontiguousarray(
                _crop(padded, self.ch, self.cw, "random", self._rng))
            yield img


class HFlip(Transformer):
    """Horizontal flip with probability `threshold`
    (≙ image/HFlip.scala)."""

    def __init__(self, threshold: float = 0.5, seed: int = 0):
        self.threshold = threshold
        self._rng = np.random.RandomState(seed)

    def apply_iter(self, it):
        for img in it:
            if self._rng.uniform() < self.threshold:
                img.data = np.ascontiguousarray(img.data[:, ::-1])
            yield img


# --------------------------------------------------------------------- #
# normalization                                                         #
# --------------------------------------------------------------------- #
class BGRImgNormalizer(Transformer):
    """(img - mean) / std per channel; means/stds either given or estimated
    from a dataset pass (≙ image/BGRImgNormalizer.scala)."""

    def __init__(self, mean: Sequence[float], std: Sequence[float]):
        self.mean = np.asarray(mean, np.float32).reshape(1, 1, -1)
        self.std = np.asarray(std, np.float32).reshape(1, 1, -1)

    @staticmethod
    def from_dataset(images: Iterable[LabeledBGRImage],
                     samples: int = 10000) -> "BGRImgNormalizer":
        tot = np.zeros(3, np.float64)
        tot2 = np.zeros(3, np.float64)
        n = 0
        for i, img in enumerate(images):
            if i >= samples:
                break
            tot += img.data.reshape(-1, 3).sum(0)
            tot2 += (img.data.reshape(-1, 3) ** 2).sum(0)
            n += img.data.shape[0] * img.data.shape[1]
        mean = tot / n
        std = np.sqrt(tot2 / n - mean ** 2)
        return BGRImgNormalizer(mean, std)

    def apply_iter(self, it):
        for img in it:
            img.data = (img.data - self.mean) / self.std
            yield img


class BGRImgPixelNormalizer(Transformer):
    """Subtract a per-pixel mean image (≙ image/BGRImgPixelNormalizer.scala)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def apply_iter(self, it):
        for img in it:
            img.data = img.data - self.means
            yield img


class GreyImgNormalizer(Transformer):
    """≙ image/GreyImgNormalizer.scala."""

    def __init__(self, mean: float, std: float):
        self.mean = float(mean)
        self.std = float(std)

    @staticmethod
    def from_dataset(images, samples: int = 10000) -> "GreyImgNormalizer":
        tot = tot2 = 0.0
        n = 0
        for i, img in enumerate(images):
            if i >= samples:
                break
            tot += float(img.data.sum())
            tot2 += float((img.data ** 2).sum())
            n += img.data.size
        mean = tot / n
        return GreyImgNormalizer(mean, np.sqrt(tot2 / n - mean ** 2))

    def apply_iter(self, it):
        for img in it:
            img.data = (img.data - self.mean) / self.std
            yield img


# --------------------------------------------------------------------- #
# color augmentation                                                    #
# --------------------------------------------------------------------- #
def _grayscale_bgr(img: np.ndarray) -> np.ndarray:
    # reference grayScale walks BGR triples: B*0.299 + G*0.587 + R*0.114
    # (image/ColorJitter.scala grayScale)
    g = (img[..., 0] * 0.299 + img[..., 1] * 0.587 + img[..., 2] * 0.114)
    return np.repeat(g[..., None], 3, axis=-1)


class ColorJitter(Transformer):
    """Random-order brightness/contrast/saturation, each strength 0.4
    (≙ image/ColorJitter.scala)."""

    def __init__(self, brightness: float = 0.4, contrast: float = 0.4,
                 saturation: float = 0.4, seed: int = 0):
        self.strength = {"brightness": brightness, "contrast": contrast,
                         "saturation": saturation}
        self._rng = np.random.RandomState(seed)

    def _blend(self, a, b, alpha):
        return a * alpha + (1.0 - alpha) * b

    def _jitter(self, img: np.ndarray) -> np.ndarray:
        order = list(self.strength)
        self._rng.shuffle(order)
        for key in order:
            var = self.strength[key]
            alpha = 1.0 + float(self._rng.uniform(-var, var))
            if key == "brightness":
                img = self._blend(img, np.zeros_like(img), alpha)
            elif key == "contrast":
                target = np.full_like(img, _grayscale_bgr(img).mean())
                img = self._blend(img, target, alpha)
            else:  # saturation
                img = self._blend(img, _grayscale_bgr(img), alpha)
        return img

    def apply_iter(self, it):
        for img in it:
            img.data = self._jitter(img.data)
            yield img


class Lighting(Transformer):
    """AlexNet fancy-PCA lighting noise (≙ image/Lighting.scala; same
    eigval/eigvec constants, alphastd=0.1).  Operates on BGR data by
    applying the RGB perturbation reversed."""

    alphastd = 0.1
    eigval = np.array([0.2175, 0.0188, 0.0045], np.float32)
    eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                       [-0.5808, -0.0045, -0.8140],
                       [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, seed: int = 0):
        self._rng = np.random.RandomState(seed)

    def apply_iter(self, it):
        for img in it:
            alpha = self._rng.normal(0, self.alphastd, 3).astype(np.float32)
            rgb = (self.eigvec * alpha[None, :] * self.eigval[None, :]).sum(1)
            img.data = img.data + rgb[::-1][None, None, :]  # BGR order
            yield img


# --------------------------------------------------------------------- #
# to Sample / MiniBatch                                                 #
# --------------------------------------------------------------------- #
class BGRImgToSample(Transformer):
    """HWC BGR -> CHW Sample, optionally reordered to RGB
    (≙ image/BGRImgToSample.scala)."""

    def __init__(self, to_rgb: bool = True):
        self.to_rgb = to_rgb

    def apply_iter(self, it):
        for img in it:
            data = img.data[..., ::-1] if self.to_rgb else img.data
            chw = np.ascontiguousarray(np.transpose(data, (2, 0, 1)))
            yield Sample(chw, np.float32(img.label))


class GreyImgToSample(Transformer):
    """HW -> (1,H,W) Sample (≙ image/GreyImgToSample.scala)."""

    def apply_iter(self, it):
        for img in it:
            yield Sample(img.data[None, ...], np.float32(img.label))


class BGRImgToBatch(Transformer):
    """Images -> NCHW MiniBatch in one shot (≙ image/BGRImgToBatch.scala +
    MTLabeledBGRImgToBatch.scala: the multi-thread copy becomes one
    vectorised stack)."""

    def __init__(self, batch_size: int, to_rgb: bool = True,
                 drop_last: bool = False):
        self.batch_size = batch_size
        self.to_rgb = to_rgb
        self.drop_last = drop_last

    def apply_iter(self, it):
        buf: List[LabeledBGRImage] = []
        for img in it:
            buf.append(img)
            if len(buf) == self.batch_size:
                yield self._batch(buf)
                buf = []
        if buf and not self.drop_last:
            yield self._batch(buf)

    def _batch(self, buf):
        data = np.stack([b.data for b in buf])
        if self.to_rgb:
            data = data[..., ::-1]
        x = np.ascontiguousarray(np.transpose(data, (0, 3, 1, 2)))
        y = np.asarray([b.label for b in buf], np.float32)
        return MiniBatch(x, y)


class GreyImgToBatch(Transformer):
    """≙ image/GreyImgToBatch.scala."""

    def __init__(self, batch_size: int, drop_last: bool = False):
        self.batch_size = batch_size
        self.drop_last = drop_last

    def apply_iter(self, it):
        buf: List[LabeledGreyImage] = []
        for img in it:
            buf.append(img)
            if len(buf) == self.batch_size:
                yield self._batch(buf)
                buf = []
        if buf and not self.drop_last:
            yield self._batch(buf)

    def _batch(self, buf):
        x = np.stack([b.data for b in buf])[:, None, :, :]
        y = np.asarray([b.label for b in buf], np.float32)
        return MiniBatch(np.ascontiguousarray(x), y)


# --------------------------------------------------------------------- #
# Hadoop SequenceFile interop (the reference's ImageNet storage format) #
# --------------------------------------------------------------------- #
class BGRImgToLocalSeqFile(Transformer):
    """Write images into numbered .seq shards, `block_size` per file,
    yielding each file name (≙ image/BGRImgToLocalSeqFile.scala: key =
    Text(label) [or "name\\nlabel"], value = Text(int32BE width, int32BE
    height, BGR uint8 bytes))."""

    def __init__(self, block_size: int, base_file_name: str,
                 has_name: bool = False):
        self.block_size = block_size
        self.base = base_file_name
        self.has_name = has_name
        self._index = 0

    def apply_iter(self, it):
        import struct
        from ..utils.seqfile import SequenceFileWriter
        it = iter(it)
        done = False
        while not done:
            fname = f"{self.base}_{self._index}.seq"
            with SequenceFileWriter(fname) as w:
                count = 0
                while count < self.block_size:
                    try:
                        item = next(it)
                    except StopIteration:
                        done = True
                        break
                    if isinstance(item, tuple):
                        img, name = item
                    else:
                        img, name = item, ""
                    header = struct.pack(">ii", img.width, img.height)
                    payload = header + np.clip(img.data, 0, 255) \
                        .astype(np.uint8).tobytes()
                    key = (f"{name}\n{int(img.label)}" if self.has_name
                           else f"{int(img.label)}").encode()
                    w.append(key, payload)
                    count += 1
            if count:
                self._index += 1
                yield fname
            elif done:
                import os
                os.remove(fname)


class LocalSeqFileToBytes(Transformer):
    """File names -> (HWC uint8 BGR array, label) pairs feeding
    BytesToBGRImg (≙ image/LocalSeqFileToBytes.scala)."""

    def apply_iter(self, it):
        import struct
        from ..utils.seqfile import SequenceFileReader
        for fname in it:
            for key, value in SequenceFileReader(fname):
                w, h = struct.unpack(">ii", value[:8])
                arr = np.frombuffer(value[8:8 + w * h * 3], np.uint8) \
                    .reshape(h, w, 3)
                text = key.decode()
                label = float(text.split("\n")[-1])
                yield arr, label
