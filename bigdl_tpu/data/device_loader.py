"""Background host→device staging (≙ the reference Engine's prefetching
data pipeline: dataset/DataSet.scala iterators feed a thread pool so the
compute thread never blocks on IO/conversion).

On TPU the equivalent stall is host staging: numpy conversion +
``jax.device_put`` of the next minibatch serialize with the device
dispatch when done inline.  :class:`DeviceLoader` runs the producer
iterator (conversion + placement included) on a background thread with a
bounded queue, so batch N+1 stages into HBM while step N executes —
classic double buffering for ``depth=2``.

Used by ``Optimizer.set_prefetch(depth)``; composable with the native
record prefetcher (bigdl_tpu.native.NativePrefetcher) for the file->host
half of the pipeline.
"""
from __future__ import annotations

import queue
import threading


class _End:
    pass


class _Raise:
    def __init__(self, exc):
        self.exc = exc


class DeviceLoader:
    """Iterate ``source`` on a background thread, ``depth`` items ahead.

    The producer thread runs everything the source generator does —
    decode, augment, device_put (jax dispatch is thread-safe) — and
    exceptions re-raise at the consumer's next pull.  Early consumer exit
    (break / GC) signals the producer to stop instead of deadlocking on
    the bounded queue.
    """

    def __init__(self, source, depth: int = 2):
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self.source = source
        self.depth = depth

    def __iter__(self):
        q: queue.Queue = queue.Queue(self.depth)
        stop = threading.Event()

        def fill():
            try:
                for item in self.source:
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
                q.put(_End())
            except BaseException as e:  # re-raised on the consumer side
                try:
                    q.put(_Raise(e), timeout=1.0)
                except queue.Full:
                    pass

        t = threading.Thread(target=fill, daemon=True,
                             name="bigdl-tpu-device-loader")
        t.start()
        try:
            while True:
                item = q.get()
                if isinstance(item, _End):
                    return
                if isinstance(item, _Raise):
                    raise item.exc
                yield item
        finally:
            stop.set()
