"""Background host→device staging (≙ the reference Engine's prefetching
data pipeline: dataset/DataSet.scala iterators feed a thread pool so the
compute thread never blocks on IO/conversion).

On TPU the equivalent stall is host staging: numpy conversion +
``jax.device_put`` of the next minibatch serialize with the device
dispatch when done inline.  :class:`DeviceLoader` runs the producer
iterator (conversion + placement included) on a background thread with a
bounded queue, so batch N+1 stages into HBM while step N executes —
classic double buffering for ``depth=2``.

Used by ``Optimizer.set_prefetch(depth)``; composable with the native
record prefetcher (bigdl_tpu.native.NativePrefetcher) for the file->host
half of the pipeline.
"""
from __future__ import annotations

import queue
import threading
import time


class _End:
    pass


class _Raise:
    def __init__(self, exc):
        self.exc = exc


class DeviceLoader:
    """Iterate ``source`` on a background thread, ``depth`` items ahead.

    The producer thread runs everything the source generator does —
    decode, augment, device_put (jax dispatch is thread-safe) — and
    exceptions re-raise at the consumer's next pull.  Early consumer exit
    (break / GC) signals the producer to stop instead of deadlocking on
    the bounded queue.

    Telemetry (to ``recorder``, default the process-active one —
    :func:`bigdl_tpu.observability.get_recorder`): prefetch starvation
    is invisible from step timings alone, so the consumer's blocked-on-
    empty-queue time accumulates into the ``dataloader/stall_seconds``
    counter, queue occupancy after each pull lands in the
    ``dataloader/queue_depth`` gauge, and producer back-pressure (queue
    full) into ``dataloader/producer_wait_seconds``.
    """

    def __init__(self, source, depth: int = 2, recorder=None):
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self.source = source
        self.depth = depth
        self.recorder = recorder

    def __iter__(self):
        rec = self.recorder
        if rec is None:
            from ..observability import get_recorder
            rec = get_recorder()
        q: queue.Queue = queue.Queue(self.depth)
        stop = threading.Event()

        def fill():
            try:
                for item in self.source:
                    blocked = None
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            if blocked is None:
                                blocked = time.perf_counter()
                            continue
                    if blocked is not None:
                        rec.inc("dataloader/producer_wait_seconds",
                                time.perf_counter() - blocked)
                    if stop.is_set():
                        return
                q.put(_End())
            except BaseException as e:  # re-raised on the consumer side
                try:
                    q.put(_Raise(e), timeout=1.0)
                except queue.Full:
                    pass

        t = threading.Thread(target=fill, daemon=True,
                             name="bigdl-tpu-device-loader")
        t.start()
        try:
            while True:
                t0 = time.perf_counter()
                item = q.get()
                if rec.enabled:
                    rec.inc("dataloader/stall_seconds",
                            time.perf_counter() - t0)
                    rec.gauge("dataloader/queue_depth", q.qsize())
                if isinstance(item, _End):
                    return
                if isinstance(item, _Raise):
                    raise item.exc
                rec.inc("dataloader/batches")
                yield item
        finally:
            stop.set()
