"""Pipeline prefetch (≙ the reference's MTLabeledBGRImgToBatch + Engine
thread-pool overlap of IO/augmentation with compute).

`PrefetchedDataSet` wraps any DataSet and materializes up to `depth`
batches ahead on a background thread, so host augmentation overlaps the
TPU step.  `FileRecordDataSet` streams fixed-length records through the
C++ native prefetcher (bigdl_tpu.native) when built.
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Optional, Sequence

import numpy as np

from .dataset import DataSet
from .minibatch import MiniBatch

_END = object()


class PrefetchedDataSet(DataSet):
    def __init__(self, base: DataSet, depth: int = 2):
        self.base = base
        self.depth = depth

    def size(self):
        return self.base.size()

    def shuffle(self):
        self.base.shuffle()
        return self

    def batches_per_epoch(self):
        return getattr(self.base, "batches_per_epoch", lambda: None)()

    def data(self, train=True, epoch=None):
        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        error = []

        def producer():
            try:
                try:
                    it = self.base.data(train, epoch=epoch)
                except TypeError:
                    it = self.base.data(train)
                for item in it:
                    q.put(item)
            except BaseException as e:  # surfaced on the consumer side
                error.append(e)
            finally:
                q.put(_END)

        t = threading.Thread(target=producer, daemon=True,
                             name="bigdl-prefetch")
        t.start()
        while True:
            item = q.get()
            if item is _END:
                if error:
                    raise error[0]
                return
            yield item


class FileRecordDataSet(DataSet):
    """Fixed-length records from shard files via the native prefetcher;
    `decode(record_bytes) -> Sample|MiniBatch|array` runs on the consumer
    thread (≙ LocalSeqFileToBytes + BytesToBGRImg head of the reference
    ImageNet pipeline)."""

    def __init__(self, paths: Sequence[str], record_bytes: int,
                 decode: Callable[[bytes], object],
                 header_bytes: int = 0, capacity: int = 64,
                 n_workers: int = 2):
        self.paths = list(paths)
        self.record_bytes = record_bytes
        self.decode = decode
        self.header_bytes = header_bytes
        self.capacity = capacity
        self.n_workers = n_workers
        import os
        self._n = sum(
            max(0, (os.path.getsize(p) - header_bytes) // record_bytes)
            for p in self.paths)

    def size(self):
        return self._n

    def data(self, train=True):
        from ..native import NativePrefetcher
        pf = NativePrefetcher(self.paths, self.record_bytes,
                              self.header_bytes, self.capacity,
                              self.n_workers, loop=False)
        try:
            for rec in pf:
                yield self.decode(rec)
        finally:
            pf.close()
