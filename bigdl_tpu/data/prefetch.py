"""Pipeline prefetch (≙ the reference's MTLabeledBGRImgToBatch + Engine
thread-pool overlap of IO/augmentation with compute).

`PrefetchedDataSet` wraps any DataSet and materializes up to `depth`
batches ahead on a background thread, so host augmentation overlaps the
TPU step.  `FileRecordDataSet` streams fixed-length records through the
C++ native prefetcher (bigdl_tpu.native) when built.
"""
from __future__ import annotations

import queue
import threading
import weakref
from typing import Callable, Optional, Sequence

import numpy as np

from .dataset import DataSet
from .minibatch import MiniBatch
# one canonical copy of the stop-aware queue plumbing: the streaming
# pipeline and this prefetcher must share the same abandonment
# semantics or the two loaders' shutdown behavior diverges
from .sharded import _finalize_stream as _stop_producer
from .sharded import _put as _put_stop_aware

_END = object()


def _fill(make_source: Callable, q: "queue.Queue",
          stop: threading.Event):
    """EVERY put goes through the stop-aware helper, the terminal
    sentinel included — a plain put of _END with a full queue and an
    abandoned consumer would re-create the thread leak."""
    try:
        for item in make_source():
            if not _put_stop_aware(q, item, stop):
                return
        _put_stop_aware(q, _END, stop)
    except BaseException as e:          # surfaced on the consumer side
        _put_stop_aware(q, (_END, e), stop)


class _PrefetchIterator:
    """Batch iterator whose fill thread can ALWAYS exit.

    The old generator implementation blocked the producer on a plain
    ``q.put``: a consumer that abandoned iteration early (break,
    exception, dropped reference) left the thread parked on a full
    queue forever — one leaked thread (plus ``depth`` pinned batches)
    per abandoned epoch.  Every put is now stop-aware, ``close()`` (and
    the generator-``finally`` of normal exhaustion) trips the stop
    event, and a ``weakref.finalize`` backstop — the
    ``serving/engine.py`` finalizer pattern — covers consumers that
    never call close.
    """

    def __init__(self, make_source: Callable, depth: int):
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._finalizer = weakref.finalize(self, _stop_producer,
                                           self._stop)
        # module-level target holding only (source, q, stop): a bound
        # method would keep `self` reachable from the running thread
        # and the GC finalizer could never fire while the thread lives
        self._thread = threading.Thread(
            target=_fill, args=(make_source, self._q, self._stop),
            daemon=True, name="bigdl-prefetch")
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is _END:
            self.close()
            raise StopIteration
        if isinstance(item, tuple) and len(item) == 2 \
                and item[0] is _END:
            self.close()
            raise item[1]
        return item

    def close(self):
        self._stop.set()


class PrefetchedDataSet(DataSet):
    def __init__(self, base: DataSet, depth: int = 2):
        self.base = base
        self.depth = depth

    def size(self):
        return self.base.size()

    def shuffle(self):
        self.base.shuffle()
        return self

    def batches_per_epoch(self):
        return getattr(self.base, "batches_per_epoch", lambda: None)()

    def data(self, train=True, epoch=None):
        def make_source():
            try:
                return self.base.data(train, epoch=epoch)
            except TypeError:   # dataset without epoch-seeded shuffling
                return self.base.data(train)

        it = _PrefetchIterator(make_source, self.depth)
        try:
            for item in it:
                yield item
        finally:
            it.close()      # break/exception/GC: unpark the fill thread


class FileRecordDataSet(DataSet):
    """Fixed-length records from shard files via the native prefetcher;
    `decode(record_bytes) -> Sample|MiniBatch|array` runs on the consumer
    thread (≙ LocalSeqFileToBytes + BytesToBGRImg head of the reference
    ImageNet pipeline)."""

    def __init__(self, paths: Sequence[str], record_bytes: int,
                 decode: Callable[[bytes], object],
                 header_bytes: int = 0, capacity: int = 64,
                 n_workers: int = 2):
        self.paths = list(paths)
        self.record_bytes = record_bytes
        self.decode = decode
        self.header_bytes = header_bytes
        self.capacity = capacity
        self.n_workers = n_workers
        import os
        self._n = sum(
            max(0, (os.path.getsize(p) - header_bytes) // record_bytes)
            for p in self.paths)

    def size(self):
        return self._n

    def data(self, train=True):
        from ..native import NativePrefetcher
        pf = NativePrefetcher(self.paths, self.record_bytes,
                              self.header_bytes, self.capacity,
                              self.n_workers, loop=False)
        try:
            for rec in pf:
                yield self.decode(rec)
        finally:
            pf.close()
