"""MNIST loader (≙ pyspark/bigdl/dataset/mnist.py).

Reads the standard idx .gz files from a local directory; with no files
present (zero-egress environment) generates a deterministic synthetic
set with class-dependent structure so training pipelines remain testable.
"""
from __future__ import annotations

import gzip
import os

import numpy as np

# ≙ mnist.py normalization constants
TRAIN_MEAN = 0.13066047740239506 * 255
TRAIN_STD = 0.3081078 * 255
TEST_MEAN = 0.13251460696903547 * 255
TEST_STD = 0.31048024 * 255


def _read32(stream):
    return np.frombuffer(stream.read(4),
                         dtype=np.dtype(np.uint32).newbyteorder(">"))[0]


def extract_images(path):
    with gzip.open(path, "rb") as f:
        if _read32(f) != 2051:
            raise ValueError(f"{path}: bad magic for MNIST images")
        n, rows, cols = _read32(f), _read32(f), _read32(f)
        buf = f.read(int(rows) * int(cols) * int(n))
        return np.frombuffer(buf, np.uint8).reshape(int(n), int(rows),
                                                    int(cols), 1)


def extract_labels(path):
    with gzip.open(path, "rb") as f:
        if _read32(f) != 2049:
            raise ValueError(f"{path}: bad magic for MNIST labels")
        n = _read32(f)
        return np.frombuffer(f.read(int(n)), np.uint8)


def _synthetic(n, seed):
    """Class-separable synthetic digits: class c lights a band of rows."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, n).astype(np.uint8)
    images = (rng.rand(n, 28, 28, 1) * 40).astype(np.uint8)
    for c in range(10):
        rows = slice(2 + c * 2, 5 + c * 2)
        images[labels == c, rows, 4:24] = 220
    return images, labels


def read_data_sets(train_dir, data_type="train"):
    """Returns (images [N,28,28,1] uint8, labels [N] uint8 0-based)."""
    prefix = "train" if data_type == "train" else "t10k"
    img = os.path.join(train_dir, f"{prefix}-images-idx3-ubyte.gz")
    lab = os.path.join(train_dir, f"{prefix}-labels-idx1-ubyte.gz")
    if os.path.exists(img) and os.path.exists(lab):
        return extract_images(img), extract_labels(lab)
    n = 2048 if data_type == "train" else 512
    return _synthetic(n, seed=0 if data_type == "train" else 1)


def load_data(train_dir="/tmp/mnist"):
    xtr, ytr = read_data_sets(train_dir, "train")
    xte, yte = read_data_sets(train_dir, "test")
    return (xtr, ytr), (xte, yte)
