"""Text pipeline (≙ dataset/text/: SentenceSplitter, SentenceTokenizer,
SentenceBiPadding, Dictionary, TextToLabeledSentence,
LabeledSentenceToSample, Types.scala; pyspark/bigdl/dataset/sentence.py).

Pure-python host-side preprocessing; sequences end up as padded int arrays
(static shapes for XLA).  The reference tokenizes with Apache NLP; we use a
regex tokenizer with identical pipeline semantics.
"""
from __future__ import annotations

import json
import os
import re
from collections import Counter
from typing import Iterable, Iterator, List, Optional, Sequence

import numpy as np

from .dataset import Transformer
from .minibatch import MiniBatch, Sample

SENTENCE_START = "SENTENCESTART"
SENTENCE_END = "SENTENCEEND"


class LabeledSentence:
    """Token-index sequence + per-step or scalar label
    (≙ text/Types.scala LabeledSentence)."""

    def __init__(self, data: Sequence[float], label: Sequence[float]):
        self.data = np.asarray(data, np.float32)
        self.label = np.asarray(label, np.float32)

    def data_length(self):
        return len(self.data)

    def label_length(self):
        return len(self.label)


class SentenceSplitter(Transformer):
    """Text blob -> sentences (≙ text/SentenceSplitter.scala; regex instead
    of the reference's OpenNLP model download)."""

    _pat = re.compile(r"(?<=[.!?])\s+")

    def apply_iter(self, it):
        for text in it:
            for s in self._pat.split(text.strip()):
                if s:
                    yield s


class SentenceTokenizer(Transformer):
    """Sentence -> token list (≙ text/SentenceTokenizer.scala)."""

    _pat = re.compile(r"[A-Za-z0-9']+|[.,!?;:]")

    def __init__(self, lower: bool = True):
        self.lower = lower

    def tokenize(self, sentence: str) -> List[str]:
        toks = self._pat.findall(sentence)
        return [t.lower() for t in toks] if self.lower else toks

    def apply_iter(self, it):
        for s in it:
            yield self.tokenize(s)


class SentenceBiPadding(Transformer):
    """tokens -> [start] + tokens + [end] (≙ text/SentenceBiPadding.scala)."""

    def __init__(self, start: Optional[str] = None, end: Optional[str] = None):
        self.start = start or SENTENCE_START
        self.end = end or SENTENCE_END

    def apply_iter(self, it):
        for toks in it:
            if isinstance(toks, str):
                yield f"{self.start} {toks} {self.end}"
            else:
                yield [self.start] + list(toks) + [self.end]


class Dictionary:
    """Top-k vocabulary with discard list (≙ text/Dictionary.scala).
    Out-of-vocab words map to index `vocab_size` (the reference's
    getOrElse(word, _vocabSize))."""

    def __init__(self, sentences: Optional[Iterable[Sequence[str]]] = None,
                 vocab_size: Optional[int] = None):
        self._word2index = {}
        self._index2word = {}
        self._discard_vocab: List[str] = []
        if sentences is not None:
            freq = Counter()
            for toks in sentences:
                freq.update(toks)
            ordered = [w for w, _ in freq.most_common()]
            keep = ordered if vocab_size is None else ordered[:vocab_size]
            self._discard_vocab = [] if vocab_size is None \
                else ordered[vocab_size:]
            for i, w in enumerate(keep):
                self._word2index[w] = i
                self._index2word[i] = w

    # ≙ Dictionary.scala API
    def get_vocab_size(self) -> int:
        return len(self._word2index)

    def get_discard_size(self) -> int:
        return len(self._discard_vocab)

    def vocabulary(self) -> List[str]:
        return [self._index2word[i] for i in range(len(self._index2word))]

    def discard_vocab(self) -> List[str]:
        return list(self._discard_vocab)

    def get_index(self, word: str) -> int:
        return self._word2index.get(word, len(self._word2index))

    def get_word(self, index: int) -> str:
        if index in self._index2word:
            return self._index2word[index]
        if self._discard_vocab:
            return self._discard_vocab[
                np.random.randint(len(self._discard_vocab))]
        return self._index2word[np.random.randint(len(self._index2word))]

    def word2index(self):
        return dict(self._word2index)

    def index2word(self):
        return dict(self._index2word)

    def save(self, folder: str):
        os.makedirs(folder, exist_ok=True)
        with open(os.path.join(folder, "dictionary.json"), "w") as f:
            json.dump({"word2index": self._word2index,
                       "discard": self._discard_vocab}, f)

    @staticmethod
    def load(folder: str) -> "Dictionary":
        d = Dictionary()
        with open(os.path.join(folder, "dictionary.json")) as f:
            blob = json.load(f)
        d._word2index = {k: int(v) for k, v in blob["word2index"].items()}
        d._index2word = {v: k for k, v in d._word2index.items()}
        d._discard_vocab = blob["discard"]
        return d


class TextToLabeledSentence(Transformer):
    """Token list -> LabeledSentence for next-word LM training: data =
    indices[:-1], label = indices[1:]
    (≙ text/TextToLabeledSentence.scala)."""

    def __init__(self, dictionary: Dictionary):
        self.dictionary = dictionary

    def apply_iter(self, it):
        for toks in it:
            idx = [self.dictionary.get_index(t) for t in toks]
            if len(idx) < 2:
                continue
            yield LabeledSentence(idx[:-1], idx[1:])


class LabeledSentenceToSample(Transformer):
    """LabeledSentence -> Sample; either one-hot features (vocab_length set)
    or raw index features; pads to fixed lengths for static XLA shapes
    (≙ text/LabeledSentenceToSample.scala)."""

    def __init__(self, vocab_length: Optional[int] = None,
                 fixed_data_length: Optional[int] = None,
                 fixed_label_length: Optional[int] = None):
        self.vocab_length = vocab_length
        self.fixed_data_length = fixed_data_length
        self.fixed_label_length = fixed_label_length

    def apply_iter(self, it):
        for s in it:
            dlen = self.fixed_data_length or s.data_length()
            llen = self.fixed_label_length or s.label_length()
            if self.vocab_length:
                feat = np.zeros((dlen, self.vocab_length), np.float32)
                n = min(s.data_length(), dlen)
                feat[np.arange(n), s.data[:n].astype(np.int64)] = 1.0
                if s.data_length() < dlen:  # pad with the last word one-hot
                    feat[n:, int(s.data[n - 1])] = 1.0
            else:
                feat = np.zeros(dlen, np.float32)
                n = min(s.data_length(), dlen)
                feat[:n] = s.data[:n]
            # labels are 1-based for ClassNLL
            lab = np.full(llen, 1.0, np.float32)
            m = min(s.label_length(), llen)
            lab[:m] = s.label[:m] + 1.0
            yield Sample(feat, lab)


def read_localfile(path: str) -> List[str]:
    """≙ pyspark/bigdl/dataset/sentence.py read_localfile."""
    with open(path) as f:
        return [line.strip() for line in f if line.strip()]


def sentences_split(line: str) -> List[str]:
    return list(SentenceSplitter()([line]))


def sentences_bipadding(sent: str) -> str:
    return f"{SENTENCE_START} {sent} {SENTENCE_END}"


def sentence_tokenizer(sentences: Iterable[str]) -> List[List[str]]:
    tok = SentenceTokenizer()
    return [tok.tokenize(s) for s in sentences]
