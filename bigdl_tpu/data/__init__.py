"""bigdl_tpu.data — dataset & transformer pipeline (≙ com.intel.analytics.bigdl.dataset)."""
from .minibatch import Sample, MiniBatch, PaddingParam, samples_to_minibatch
from .dataset import (DataSet, LocalArrayDataSet, ArrayMiniBatchDataSet,
                      DistributedDataSet, TransformedDataSet, Transformer,
                      ChainedTransformer, SampleToMiniBatch,
                      FunctionTransformer)
