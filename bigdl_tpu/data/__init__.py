"""bigdl_tpu.data — dataset & transformer pipeline (≙ com.intel.analytics.bigdl.dataset)."""
from .minibatch import Sample, MiniBatch, PaddingParam, samples_to_minibatch
from .dataset import (DataSet, LocalArrayDataSet, ArrayMiniBatchDataSet,
                      DistributedDataSet, TransformedDataSet, Transformer,
                      ChainedTransformer, SampleToMiniBatch,
                      FunctionTransformer)
from .image import (LabeledBGRImage, LabeledGreyImage, BytesToBGRImg,
                    BytesToGreyImg, LocalImgReader, local_image_paths,
                    BGRImgCropper, GreyImgCropper, BGRImgRdmCropper, HFlip,
                    BGRImgNormalizer, BGRImgPixelNormalizer,
                    GreyImgNormalizer, ColorJitter, Lighting, BGRImgToSample,
                    GreyImgToSample, BGRImgToBatch, GreyImgToBatch)
from .imageframe import (ImageFeature, ImageFrame, FeatureTransformer,
                         ChainedFeatureTransformer, PipelineStep, Resize,
                         AspectScale, RandomResize, CenterCrop, RandomCrop,
                         FixedCrop, RandomCropper, RandomAlterAspect, Expand,
                         Filler, HFlipVision, RandomTransformer, Brightness,
                         Contrast, Saturation, Hue, ColorJitterVision,
                         ChannelNormalize, ChannelScaledNormalizer,
                         PixelNormalizer, ChannelOrder, MatToTensor,
                         ImageFrameToSample, RoiNormalize, RoiHFlip,
                         RoiResize, RoiProject, DetectionCrop,
                         RandomSampler, RandomAspectScale, BytesToMat,
                         PixelBytesToMat, MatToFloats, Pipeline,
                         LocalImageFrame, DistributedImageFrame,
                         FixExpand, SeqFileFolder)
from .sharded import (ShardedRecordDataSet, plan_epoch, epoch_order,
                      replan_cursors, iter_tfrecord_salvage,
                      iter_seqfile_salvage, iter_fixed_records,
                      count_records)
from .text import (LabeledSentence, SentenceSplitter, SentenceTokenizer,
                   SentenceBiPadding, Dictionary, TextToLabeledSentence,
                   LabeledSentenceToSample, read_localfile, sentences_split,
                   sentences_bipadding, sentence_tokenizer,
                   SENTENCE_START, SENTENCE_END)
from . import mnist, cifar, news20, movielens
