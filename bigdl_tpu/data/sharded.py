"""Sharded streaming input pipeline — the production data plane.

The reference feeds ImageNet from Hadoop SequenceFile shards through a
thread pool that overlaps IO/augmentation with compute
(``MTLabeledBGRImgToBatch`` + the Engine's prefetching iterators).  This
module is that design done TPU-native, built to keep
``data/input_stall_seconds`` ≈ 0 at the post-PR-8 step rate:

  1. **Deterministic shard planning** — every epoch, the file list is
     permuted by a seeded shuffle (:func:`epoch_order`, a pure function
     of ``(seed, epoch)``) and split at FILE level across
     ``process_count × n_workers`` global readers
     (:func:`plan_epoch`); the uneven tail (file count not divisible by
     worker count) just gives some workers one more file.  The union of
     all workers' assignments is every file exactly once per epoch.

  2. **Parallel host decode** — each local worker runs a thread that
     streams records out of its files (TFRecord / SequenceFile /
     fixed-length framing, CRC-resync salvage over corrupt regions —
     the PR-4 ``read_events(salvage=True)`` pattern) and decodes them
     off the consumer's critical path.  The batcher drains the worker
     queues in deterministic round-robin, so the emitted sample order
     depends only on the plan — never on thread scheduling.

  3. **Owned-buffer staging** — batches are collated with copying
     ``np.stack`` (never views into a read buffer) and handed to a
     staging thread that runs ``place_fn`` (``device_put`` with the
     trainer's batch sharding) ``staging_depth`` batches ahead:
     double-buffered h2d that overlaps the device step.

  4. **Deterministic data cursor** — every emitted batch carries the
     exact read position (per-worker remaining ``[file, offset]``
     lists + round-robin pointer); :meth:`ShardedRecordDataSet.state`
     returns the cursor of the last batch the CONSUMER pulled, so a
     checkpoint taken between steps resumes with no sample re-seen or
     skipped.  :func:`replan_cursors` redistributes the remaining work
     of an epoch across a different worker/host count (the PR-6
     elastic path's data-plane half).

Determinism contract (what the tests assert):

  * same config + same cursor  → bit-identical sample sequence;
  * any worker/host replan     → exactly-once (set-identical remainder,
    no duplicates), order may differ;
  * the global batch stream never depends on the device mesh, so a
    dp4→dp2 elastic resume replays the identical sequence.

Telemetry (``data/*`` family, registered in docs/observability.md):
``data/input_stall_seconds`` (consumer blocked on an empty staging
queue — THE number this module exists to zero), ``data/queue_depth``,
``data/h2d_bytes``, ``data/decode_seconds``, ``data/records_read``,
``data/resync_skipped_bytes``, ``data/batches``,
``data/files_skipped`` (shards abandoned after retries — degradation,
never silence: each one also lands as a ``health_event``).

Transient-fault posture: shard opens and record reads run under a
:class:`~bigdl_tpu.utils.retry.RetryPolicy` — a transient EIO re-reads
the file from the current record index (yielded-record indices are
stable, so nothing is re-seen or skipped); on giveup (or a fatal errno
like EACCES) the worker SKIPS that file with a loud
``data/files_skipped`` count + health event instead of killing the
epoch.  The ``data.shard_open`` / ``data.record_read`` sites of
:mod:`bigdl_tpu.faults` make both paths testable.
"""
from __future__ import annotations

import os
import queue
import random
import struct
import threading
import time
import weakref
from typing import Callable, List, Optional, Sequence

import numpy as np

from .dataset import DataSet
from .. import faults as faultplane
from ..utils.crc32c import masked_crc32c
from ..utils.retry import RetryPolicy

CURSOR_VERSION = 1

_END = object()      # one per worker stream, then the stream is done
_STOPPED = object()  # _get() observed the stop event
_WEND = ("end",)     # batcher consumed a worker's terminal sentinel


class _RaiseItem:
    def __init__(self, exc):
        self.exc = exc


class _DecodeFailure(Exception):
    """Wrapper that carries a user decode() exception PAST the worker's
    I/O-error handling: a decode bug must surface at the consumer even
    when it happens to raise OSError (a missing side file, say) — the
    retry-then-skip degradation is for shard I/O only."""

    def __init__(self, error):
        super().__init__(repr(error))
        self.error = error


def _put(q: "queue.Queue", item, stop: threading.Event,
         timeout: float = 0.1) -> bool:
    """Stop-aware bounded put: never blocks forever on an abandoned
    consumer (the PrefetchedDataSet leak class, closed by design)."""
    while not stop.is_set():
        try:
            q.put(item, timeout=timeout)
            return True
        except queue.Full:
            continue
    return False


def _get(q: "queue.Queue", stop: threading.Event, timeout: float = 0.1):
    while not stop.is_set():
        try:
            return q.get(timeout=timeout)
        except queue.Empty:
            continue
    return _STOPPED


# --------------------------------------------------------------------- #
# shard planning: pure functions of (files, seed, epoch, world)         #
# --------------------------------------------------------------------- #
def epoch_order(n_files: int, seed: int, epoch: int) -> List[int]:
    """Seeded per-epoch permutation of file indices — a pure function of
    ``(seed, epoch)``, so every host (and every resumed run) derives the
    identical order without coordination."""
    rng = np.random.RandomState(
        (int(seed) * 1000003 + int(epoch) * 7919 + 17) % (2 ** 31 - 1))
    idx = np.arange(n_files)
    rng.shuffle(idx)
    return [int(i) for i in idx]


def plan_epoch(n_files: int, seed: int, epoch: int, process_index: int,
               process_count: int, n_workers: int,
               shuffle: bool = True) -> List[List[List[int]]]:
    """This host's per-worker file plans for one epoch.

    Returns ``[worker][k] = [file_index, start_record]`` — global worker
    ``g = process_index * n_workers + worker`` takes files
    ``order[g::world]``.  Disjoint across the world by construction and
    exhaustive (every file lands on exactly one worker), including the
    uneven tail where ``world`` does not divide the file count.
    """
    if not (0 <= process_index < process_count):
        raise ValueError(f"process_index {process_index} outside "
                         f"process_count {process_count}")
    order = epoch_order(n_files, seed, epoch) if shuffle \
        else list(range(n_files))
    world = process_count * n_workers
    plans = []
    for w in range(n_workers):
        g = process_index * n_workers + w
        plans.append([[fi, 0] for fi in order[g::world]])
    return plans


def _deal_round_robin(worker_lists: Sequence[Sequence[Sequence[int]]],
                      n_slots: int) -> List[List[List[int]]]:
    """Flatten remaining ``[file, offset]`` entries in round-robin order
    across the old workers (entry k of every worker before entry k+1 —
    approximately preserving the original interleave) and deal them
    round-robin onto ``n_slots`` new workers.  The union of entries is
    untouched, so exactly-once survives any regrouping.  Shared by
    :func:`replan_cursors` and the local replan in
    :meth:`ShardedRecordDataSet.restore` — the two MUST stay in
    lockstep or a resumed stream diverges from a replanned one."""
    remaining: List[List[int]] = []
    depth = max((len(w) for w in worker_lists), default=0)
    for k in range(depth):
        for w in worker_lists:
            if k < len(w):
                remaining.append([int(w[k][0]), int(w[k][1])])
    dealt: List[List[List[int]]] = [[] for _ in range(n_slots)]
    for i, entry in enumerate(remaining):
        dealt[i % n_slots].append(entry)
    return dealt


def replan_cursors(states: Sequence[dict], process_count: int,
                   n_workers: int,
                   n_files: Optional[int] = None) -> List[dict]:
    """Redistribute the remaining work of one epoch's cursors onto a
    NEW ``process_count × n_workers`` world (the elastic-resume path: a
    job that shrank from 2 hosts to 1 hands both hosts' cursors in and
    gets one host's cursor out).

    Every host's cursor covers only its own workers, so a host-count
    change needs EVERY old host's state — a missing host's files would
    silently be skipped, so incompleteness raises.  A fresh cursor
    (``workers: None`` — that host had not started the epoch) stands
    for its FULL epoch plan; expanding it needs the shard-file count,
    so pass ``n_files=len(paths)`` when any state may be fresh.
    Exactly-once is preserved: the union of remaining entries is
    regrouped, never changed.  Subsequent epochs are planned fresh for
    the new world.
    """
    if not states:
        raise ValueError("replan_cursors needs at least one cursor")
    base = states[0]
    old_pc = int(base.get("process_count", 1))
    for s in states[1:]:
        if (s.get("seed"), s.get("epoch")) != (base.get("seed"),
                                               base.get("epoch")):
            raise ValueError("cursors disagree on (seed, epoch): "
                             "they are not from one run")
        if int(s.get("process_count", 1)) != old_pc:
            raise ValueError("cursors disagree on process_count: "
                             "they are not from one run")
    covered = {}
    for s in states:
        pi = int(s.get("process_index", 0))
        if pi in covered:
            raise ValueError(f"duplicate cursor for process {pi}")
        covered[pi] = s
    missing = sorted(set(range(old_pc)) - set(covered))
    if missing:
        raise ValueError(
            f"replan_cursors needs every old host's cursor; missing "
            f"process(es) {missing} of {old_pc} — their remaining "
            "files would silently be skipped")
    old_workers = []
    for pi in sorted(covered):
        s = covered[pi]
        if s.get("workers") is not None:
            old_workers.extend(s["workers"])
            continue
        # fresh cursor: this host had not started the epoch, so its
        # remaining work is its ENTIRE epoch plan
        if n_files is None:
            raise ValueError(
                f"process {pi}'s cursor is a fresh epoch start "
                "(workers: None); expanding it needs "
                "n_files=len(paths)")
        old_workers.extend(plan_epoch(
            int(n_files), int(base.get("seed", 0)),
            int(base.get("epoch", 0)), pi, old_pc,
            int(s.get("n_workers", 1))))
    dealt = _deal_round_robin(old_workers, process_count * n_workers)
    out = []
    for p in range(process_count):
        out.append({
            "version": CURSOR_VERSION,
            "seed": base.get("seed"), "epoch": base.get("epoch"),
            "process_index": p, "process_count": process_count,
            "n_workers": n_workers, "rr": 0,
            "workers": dealt[p * n_workers:(p + 1) * n_workers],
        })
    return out


# --------------------------------------------------------------------- #
# record streams: framing + CRC-resync salvage per format               #
# --------------------------------------------------------------------- #
def _frame_tfrecord(data: bytes, i: int):
    """Frame one TFRecord at offset ``i``; ``(payload, next)`` when both
    masked CRCs verify, else None (same check as the PR-4 salvage
    reader — the frame check IS the resync condition)."""
    if i + 12 > len(data):
        return None
    header = data[i:i + 8]
    (length,) = struct.unpack("<Q", header)
    (hcrc,) = struct.unpack("<I", data[i + 8:i + 12])
    if masked_crc32c(header) != hcrc:
        return None
    if i + 12 + length + 4 > len(data):
        return None
    payload = data[i + 12:i + 12 + length]
    (pcrc,) = struct.unpack("<I", data[i + 12 + length:i + 16 + length])
    if masked_crc32c(payload) != pcrc:
        return None
    return payload, i + 12 + length + 4


def iter_tfrecord_salvage(path: str, start: int = 0, salvage: bool = True,
                          on_skip: Optional[Callable[[int], None]] = None):
    """Yield TFRecord payloads from record index ``start``.

    ``salvage=True`` scans past corrupt regions to the next offset that
    frames (both CRCs verify) instead of failing the file; each skipped
    byte range is reported through ``on_skip(n_bytes)``.  Record indices
    count YIELDED records, so they are stable across re-reads — a
    resumed cursor skips the same corrupt region the original pass did.
    """
    with open(path, "rb") as f:
        data = f.read()
    i, n = 0, 0
    while i + 12 <= len(data):
        framed = _frame_tfrecord(data, i)
        if framed is None:
            if not salvage:
                raise IOError(f"{path}: corrupt TFRecord at byte {i}")
            j = i + 1
            while j + 12 <= len(data) and _frame_tfrecord(data, j) is None:
                j += 1
            if j + 12 > len(data):
                j = len(data)           # trailing garbage: skip the tail
            if on_skip is not None:
                on_skip(j - i)
            i = j
            continue
        payload, i = framed
        if n >= start:
            yield payload
        n += 1
    if salvage and 0 < len(data) - i and on_skip is not None:
        on_skip(len(data) - i)          # torn tail shorter than a header


def iter_seqfile_salvage(path: str, start: int = 0, salvage: bool = True,
                         on_skip: Optional[Callable[[int], None]] = None):
    """Yield SequenceFile ``(key, value)`` pairs from record ``start``,
    resyncing on the 16-byte sync marker (``-1`` escape + sync) when a
    record's framing is implausible — the format has no per-record CRC,
    so plausibility (non-negative lengths that fit the file) is the
    corruption signal and the sync marker is the recovery point."""
    from ..utils.seqfile import SequenceFileReader
    r = SequenceFileReader(path)
    data, sync = r.data, r.sync
    escape = struct.pack(">i", -1) + sync
    pos, n = r._start, 0
    while pos + 4 <= len(data):
        (rec_len,) = struct.unpack_from(">i", data, pos)
        if rec_len == -1:
            if data[pos + 4:pos + 20] == sync:
                pos += 20
                continue
            rec_len = -2                # -1 without the sync: corrupt
        # layout: rec_len(4) | key_len(4) | key | value, where
        # rec_len = len(key bytes) + len(value bytes)
        ok = rec_len >= 0 and pos + 8 + rec_len <= len(data)
        if ok:
            (key_len,) = struct.unpack_from(">i", data, pos + 4)
            ok = 0 <= key_len <= rec_len
        if not ok:
            if not salvage:
                raise IOError(f"{path}: corrupt SequenceFile record at "
                              f"byte {pos}")
            j = data.find(escape, pos + 1)
            j = len(data) if j < 0 else j
            if on_skip is not None:
                on_skip(j - pos)
            pos = j
            continue
        body = data[pos + 8:pos + 8 + rec_len]
        key = r._deserialize(body[:key_len], r.key_class)
        value = r._deserialize(body[key_len:], r.value_class)
        pos += 8 + rec_len
        if n >= start:
            yield key, value
        n += 1


def iter_fixed_records(path: str, record_bytes: int, header_bytes: int = 0,
                       start: int = 0):
    """Yield fixed-length records from record index ``start``.  The
    native C++ prefetcher reads the file when built and the stream
    starts at 0 (its mmap readers have no seek); a mid-file resume (or
    a build-less host) takes the seeking pure-python path — identical
    records either way."""
    from .. import native
    if start == 0 and native.available():
        pf = native.NativePrefetcher([path], record_bytes, header_bytes,
                                     capacity=64, n_workers=1, loop=False)
        try:
            for rec in pf:
                yield bytes(rec)
        finally:
            pf.close()
        return
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        f.seek(header_bytes + start * record_bytes)
        while f.tell() + record_bytes <= size:
            yield f.read(record_bytes)


def count_records(path: str, fmt: str, record_bytes: Optional[int] = None,
                  header_bytes: int = 0, salvage: bool = True) -> int:
    """Number of (salvageable) records in one shard file."""
    if fmt == "fixed":
        return max(0, (os.path.getsize(path) - header_bytes)
                   // int(record_bytes))
    if fmt == "tfrecord":
        return sum(1 for _ in iter_tfrecord_salvage(path, salvage=salvage))
    if fmt == "seqfile":
        return sum(1 for _ in iter_seqfile_salvage(path, salvage=salvage))
    raise ValueError(f"unknown shard format {fmt!r}")


def _default_collate(samples):
    """(x, y) batches from (x, y) samples — copying np.stack, so the
    staged batch OWNS its memory whatever buffers decode returned."""
    first = samples[0]
    if isinstance(first, tuple) and len(first) == 2:
        xs, ys = zip(*samples)
        y0 = ys[0]
        y = None if y0 is None else np.stack([np.asarray(v) for v in ys])
        return np.stack([np.asarray(v) for v in xs]), y
    return (np.stack([np.asarray(v) for v in samples]), None)


def _host_nbytes(tree) -> int:
    total = 0
    stack = [tree]
    while stack:
        v = stack.pop()
        if isinstance(v, (tuple, list)):
            stack.extend(v)
        elif isinstance(v, dict):
            stack.extend(v.values())
        elif isinstance(v, (np.ndarray, np.generic)):
            total += v.nbytes
    return total


class _StreamIterator:
    """One epoch's batch stream: iterable, explicitly closable, and
    GC-safe — a finalizer trips the stop event so abandoned iteration
    (break / exception / dropped reference) never strands the worker or
    stager threads on a bounded queue."""

    def __init__(self, pipeline: "ShardedRecordDataSet", epoch: int,
                 cursor: Optional[dict], train: bool):
        self._pipe = pipeline
        self._epoch = int(epoch)
        self._track = train     # eval streams never move the train cursor
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._plans = None      # set by _start_stream
        self._out: "queue.Queue" = queue.Queue(pipeline.staging_depth)
        self._finalizer = weakref.finalize(self, _finalize_stream,
                                           self._stop)
        pipeline._start_stream(self, epoch, cursor, train)

    def __iter__(self):
        return self

    def __next__(self):
        rec = self._pipe._rec()
        t0 = time.perf_counter()
        while True:
            try:
                item = self._out.get(timeout=0.5)
                break
            except queue.Empty:
                if not any(t.is_alive() for t in self._threads):
                    raise RuntimeError(
                        "sharded input stream died without a terminal "
                        "item (worker/stager thread crashed hard)")
        if rec.enabled:
            rec.inc("data/input_stall_seconds",
                    time.perf_counter() - t0)
            rec.gauge("data/queue_depth", self._out.qsize())
        if item is _END:
            if self._track:
                self._pipe._mark_epoch_done(self._epoch)
            self.close()
            raise StopIteration
        if isinstance(item, _RaiseItem):
            self.close()
            raise item.exc
        batch, snap = item
        if self._track:
            self._pipe._commit_cursor(self._epoch, snap, self._plans)
        if rec.enabled:
            rec.inc("data/batches")
        return batch

    def close(self):
        self._stop.set()


def _finalize_stream(stop: threading.Event):
    stop.set()


class ShardedRecordDataSet(DataSet):
    """Multi-host auto-sharded streaming record dataset (the tentpole).

    ``paths``          shard files (every host passes the SAME list in
                       the SAME order; the planner derives this host's
                       split)
    ``fmt``            "tfrecord" | "seqfile" | "fixed"
    ``decode``         ``decode(record) -> sample`` run on the worker
                       pool (record is payload bytes; ``(key, value)``
                       for seqfile).  With ``decode_rng=True`` it is
                       called ``decode(record, rng)`` with a
                       per-record ``np.random.RandomState`` derived
                       statelessly from ``(seed, epoch, file, index)``
                       — host augmentation that resumes exactly without
                       serializing RNG streams into the cursor.
    ``batch_size``     rows per emitted batch (per HOST; the global
                       batch is ``batch_size × process_count``)
    ``n_workers``      local decode threads (file-level split)
    ``queue_depth``    per-worker decoded-sample buffer
    ``staging_depth``  placed-batch buffer (2 = classic double buffer)
    ``place_fn``       ``place_fn((x, y)) -> (x, y)`` run on the
                       staging thread — ``jax.device_put`` with the
                       trainer's batch sharding, so h2d overlaps the
                       step (the optimizers install theirs via
                       :meth:`set_place_fn`)
    ``salvage``        resync past corrupt regions instead of failing
                       the file (counted in
                       ``data/resync_skipped_bytes``)

    ``self_staging = True`` tells the optimizers this dataset already
    prefetches + stages: wrapping it in another DeviceLoader would read
    ahead of training and break the exactly-once cursor.
    """

    self_staging = True

    def __init__(self, paths: Sequence[str], fmt: str = "tfrecord",
                 decode: Optional[Callable] = None, batch_size: int = 32,
                 *, record_bytes: Optional[int] = None,
                 header_bytes: int = 0, n_workers: int = 2,
                 queue_depth: int = 16, staging_depth: int = 2,
                 seed: int = 0, process_index: int = 0,
                 process_count: int = 1, salvage: bool = True,
                 drop_last: bool = True, shuffle: bool = True,
                 collate: Optional[Callable] = None,
                 place_fn: Optional[Callable] = None,
                 decode_rng: bool = False, recorder=None,
                 read_retries: int = 3, retry_base: float = 0.05):
        if fmt not in ("tfrecord", "seqfile", "fixed"):
            raise ValueError(f"unknown shard format {fmt!r}")
        if fmt == "fixed" and not record_bytes:
            raise ValueError("fmt='fixed' needs record_bytes=")
        if not paths:
            raise ValueError("no shard files")
        if n_workers < 1 or queue_depth < 1 or staging_depth < 1:
            raise ValueError("n_workers/queue_depth/staging_depth >= 1")
        self.paths = [os.fspath(p) for p in paths]
        self.fmt = fmt
        self.decode = decode
        self.batch_size = int(batch_size)
        self.record_bytes = record_bytes
        self.header_bytes = header_bytes
        self.n_workers = int(n_workers)
        self.queue_depth = int(queue_depth)
        self.staging_depth = int(staging_depth)
        self.seed = int(seed)
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.salvage = bool(salvage)
        self.drop_last = bool(drop_last)
        self._shuffle = bool(shuffle)
        self.collate = collate or _default_collate
        self.place_fn = place_fn
        self.decode_rng = bool(decode_rng)
        self.recorder = recorder
        self.read_retries = max(1, int(read_retries))
        self.retry_base = float(retry_base)
        self._cursor: Optional[dict] = None
        self._size: Optional[int] = None

    # -- DataSet surface ------------------------------------------------ #
    def _rec(self):
        if self.recorder is not None:
            return self.recorder
        from ..observability import get_recorder
        return get_recorder()

    def size(self) -> int:
        """Total records across ALL shard files (scanned once, cached)."""
        if self._size is None:
            self._size = sum(
                count_records(p, self.fmt, self.record_bytes,
                              self.header_bytes, self.salvage)
                for p in self.paths)
        return self._size

    def batches_per_epoch(self):
        # per-host batch count is data-dependent under salvage/uneven
        # splits; None tells the trainers to just iterate
        return None

    def set_place_fn(self, fn):
        """Install the device-placement hook run on the staging thread
        (h2d overlap); the optimizers call this with their sharded
        ``_place_batch``."""
        self.place_fn = fn
        return self

    # -- cursor --------------------------------------------------------- #
    def _cursor_dict(self) -> Optional[dict]:
        """Materialize the committed cursor into its JSON dict form.

        Per-batch commits are LAZY — ``(epoch, per-worker (pos, off),
        rr, shared plans ref)``, O(n_workers) per batch — because a
        full ``workers`` snapshot is O(remaining shard files) and only
        a checkpoint actually needs it.  Materialization caches back,
        so repeated state() calls between batches are free."""
        cur = self._cursor
        if cur is None or isinstance(cur, dict):
            return cur
        epoch, pos, rr, plans = cur
        workers = []
        for w, p in enumerate(pos):
            if p is _WEND:
                workers.append([])
            elif p is None:     # nothing consumed yet: full plan
                workers.append([list(e) for e in plans[w]])
            else:
                li, off = p
                tail = plans[w][li:]
                workers.append([[tail[0][0], int(off)]]
                               + [list(e) for e in tail[1:]])
        out = {"version": CURSOR_VERSION, "seed": self.seed,
               "epoch": int(epoch),
               "process_index": self.process_index,
               "process_count": self.process_count,
               "n_workers": self.n_workers, "rr": int(rr),
               "workers": workers}
        self._cursor = out
        return out

    def state(self) -> dict:
        """Cursor of the last batch the consumer PULLED — exactly the
        samples training has consumed, whatever the worker/staging
        threads have read ahead.  JSON-safe; goes into checkpoint
        metadata.  After an epoch completes it carries ``done: True``
        (nothing remaining in that epoch; the next ``data()`` plans
        the following epoch fresh)."""
        cur = self._cursor_dict()
        if cur is None:
            return self._fresh_cursor(0)
        return dict(cur)

    def restore(self, state: dict):
        """Resume from a :meth:`state` cursor.  Same reader config →
        bit-identical continuation.  A different LOCAL worker count
        replans this host's remaining files (exactly-once preserved;
        interleave order changes).  A different host world needs every
        host's cursor — see :func:`replan_cursors`."""
        if not isinstance(state, dict) or "epoch" not in state:
            raise ValueError(f"not a data cursor: {state!r}")
        if int(state.get("version", 0)) > CURSOR_VERSION:
            raise ValueError(
                f"data cursor version {state.get('version')} is newer "
                f"than this library ({CURSOR_VERSION})")
        if state.get("seed") != self.seed:
            raise ValueError(
                f"cursor seed {state.get('seed')} != dataset seed "
                f"{self.seed}: the shard order would silently diverge")
        if state.get("workers") is None:
            self._cursor = self._fresh_cursor(int(state["epoch"]))
            return self
        same_world = (int(state.get("process_count", 1))
                      == self.process_count
                      and int(state.get("process_index", 0))
                      == self.process_index)
        if not same_world:
            raise ValueError(
                "cursor was written by process "
                f"{state.get('process_index')}/"
                f"{state.get('process_count')} but this dataset is "
                f"{self.process_index}/{self.process_count}; a host-"
                "world change must be re-planned from ALL hosts' "
                "cursors first — replan_cursors(states, process_count, "
                "n_workers)")
        workers = [[[int(f), int(o)] for f, o in w]
                   for w in state["workers"]]
        bad = sorted({f for w in workers for f, _ in w
                      if not 0 <= f < len(self.paths)})
        if bad:
            raise ValueError(
                f"cursor references shard file indices {bad} but this "
                f"dataset has {len(self.paths)} paths — the cursor was "
                "written against a different shard list (positions "
                "would mean different records; pass the same paths in "
                "the same order)")
        rr = int(state.get("rr", 0))
        if len(workers) != self.n_workers:
            # local replan: deal this host's remaining entries across
            # the new local worker count (host-local, so safe without
            # the other hosts' cursors)
            workers = _deal_round_robin(workers, self.n_workers)
            rr = 0
        self._cursor = {
            "version": CURSOR_VERSION, "seed": self.seed,
            "epoch": int(state["epoch"]),
            "process_index": self.process_index,
            "process_count": self.process_count,
            "n_workers": self.n_workers, "rr": rr, "workers": workers,
        }
        if state.get("done"):
            self._cursor["done"] = True
        return self

    def _fresh_cursor(self, epoch: int) -> dict:
        return {"version": CURSOR_VERSION, "seed": self.seed,
                "epoch": int(epoch),
                "process_index": self.process_index,
                "process_count": self.process_count,
                "n_workers": self.n_workers, "rr": 0, "workers": None}

    def _commit_cursor(self, epoch: int, snap, plans):
        # lazy commit: (epoch, per-worker positions, rr, shared plans)
        # — materialized into the dict form only when state() (a
        # checkpoint) or the next data() call asks
        pos, rr = snap
        self._cursor = (epoch, pos, rr, plans)

    def _mark_epoch_done(self, epoch: int):
        """The consumer drained this epoch's stream: record completion
        so ``data(epoch=None)`` rolls to the next epoch instead of
        resuming an empty remainder forever.  Any drop_last tail the
        batcher discarded is discarded by EVERY run of this epoch, so
        'nothing remaining' is the exactly-once-consistent record."""
        done = self._fresh_cursor(epoch)
        done["workers"] = [[] for _ in range(self.n_workers)]
        done["done"] = True
        self._cursor = done

    # -- iteration ------------------------------------------------------ #
    def data(self, train=True, epoch: Optional[int] = None):
        """One epoch's batch stream.  An EXPLICIT ``epoch`` selects the
        shard order (resuming the cursor when it matches the cursor's
        epoch — a fully-consumed epoch then yields nothing, which is
        how the optimizers detect a boundary resume); ``epoch=None``
        continues from the cursor and rolls past a completed epoch, so
        the generic ``for e: for b in ds.data(train=True)`` loop sees
        a fresh epoch each pass.  ``train=False`` streams in file
        order with no shuffle and no cursor tracking."""
        if not train:
            return _StreamIterator(self, 0, None, train=False)
        cur = self._cursor_dict()
        if epoch is None:
            if cur is None:
                epoch = 0
            elif cur.get("done"):
                epoch = cur["epoch"] + 1    # previous epoch consumed
            else:
                epoch = cur["epoch"]
        cursor = None
        if (cur is not None and cur.get("epoch") == int(epoch)
                and cur.get("workers") is not None):
            cursor = cur
        return _StreamIterator(self, int(epoch), cursor, train=True)

    def stream(self, max_epochs: Optional[int] = None):
        """Continuous batch stream across epochs (the step-driven
        SpmdTrainer feed): epochs roll over automatically, the cursor
        tracks both epoch and position."""
        done = 0
        while max_epochs is None or done < max_epochs:
            for batch in self.data(train=True, epoch=None):
                yield batch
            done += 1

    # -- the three pipeline stages -------------------------------------- #
    def _start_stream(self, it: _StreamIterator, epoch: int,
                      cursor: Optional[dict], train: bool):
        if cursor is not None:
            plans = [[[int(f), int(o)] for f, o in w]
                     for w in cursor["workers"]]
            rr = int(cursor.get("rr", 0))
        else:
            plans = plan_epoch(len(self.paths), self.seed, epoch,
                               self.process_index, self.process_count,
                               self.n_workers,
                               shuffle=self._shuffle and train)
            rr = 0
        it._plans = plans   # shared, IMMUTABLE: lazy cursors index it
        stop = it._stop
        worker_qs = [queue.Queue(self.queue_depth)
                     for _ in range(self.n_workers)]
        for w in range(self.n_workers):
            t = threading.Thread(
                target=self._worker_loop,
                args=(w, plans[w], worker_qs[w], stop, epoch),
                daemon=True, name=f"bigdl-shard-worker-{w}")
            it._threads.append(t)
        stager = threading.Thread(
            target=self._stage_loop,
            args=(worker_qs, plans, rr, epoch, it._out, stop, train),
            daemon=True, name="bigdl-shard-stager")
        it._threads.append(stager)
        for t in it._threads:
            t.start()

    def _records(self, file_index: int, start: int, on_skip):
        path = self.paths[file_index]
        if self.fmt == "tfrecord":
            return iter_tfrecord_salvage(path, start, self.salvage,
                                         on_skip)
        if self.fmt == "seqfile":
            return iter_seqfile_salvage(path, start, self.salvage,
                                        on_skip)
        return iter_fixed_records(path, self.record_bytes,
                                  self.header_bytes, start)

    def _worker_loop(self, w: int, plan, q, stop, epoch: int):
        """Stream + decode this worker's files; emit
        ``(sample, plan_pos, next_offset)`` so the batcher can cut an
        exact cursor after any sample."""
        rec = self._rec()
        stats = {"read": 0, "decode": 0.0, "skipped": 0}

        def flush(force=False):
            if not rec.enabled:
                stats.update(read=0, decode=0.0, skipped=0)
                return
            if force or stats["read"] >= 256:
                if stats["read"]:
                    rec.inc("data/records_read", stats["read"])
                if stats["decode"]:
                    rec.inc("data/decode_seconds", stats["decode"])
                if stats["skipped"]:
                    rec.inc("data/resync_skipped_bytes", stats["skipped"])
                stats.update(read=0, decode=0.0, skipped=0)

        def on_skip(n):
            stats["skipped"] += n

        # transient read errors retry per FILE from the current record
        # index (yielded-record indices are stable across re-reads, so
        # a retried file resumes exactly where it stopped — exactly-once
        # survives the retry); the jitter RNG is seeded per worker so a
        # resumed run schedules identically
        policy = RetryPolicy(
            max_attempts=self.read_retries, base=self.retry_base,
            max_delay=0.5, rng=random.Random(self.seed * 31 + w),
            recorder_fn=lambda: rec, name="data")

        try:
            for li, (fi, start) in enumerate(plan):
                off = int(start)
                # a retried attempt re-SCANS bytes the failed attempt
                # already salvaged past: replay the first `counted`
                # skip bytes silently (they were accounted) and count
                # only the excess.  Corrupt regions re-read in the same
                # order with the same sizes, so a byte-level high-water
                # mark is exact — no double count when the failure came
                # late, no undercount when it came before the first
                # yield
                counted = [0]       # skip bytes accounted for this file
                replayed = [0]      # skip bytes re-seen this attempt

                def skip_gate(n, _on_skip=on_skip, _c=counted,
                              _r=replayed):
                    fresh = max(0, _r[0] + n - _c[0])
                    _r[0] += n
                    if fresh:
                        _c[0] += fresh
                        _on_skip(fresh)

                def read_file(li=li, fi=int(fi), _r=replayed):
                    nonlocal off
                    _r[0] = 0
                    faultplane.inject("data.shard_open", rec)
                    for payload in self._records(fi, off, skip_gate):
                        faultplane.inject("data.record_read", rec)
                        t0 = time.perf_counter()
                        try:
                            if self.decode is None:
                                sample = payload
                            elif self.decode_rng:
                                sample = self.decode(
                                    payload,
                                    self._record_rng(epoch, fi, off))
                            else:
                                sample = self.decode(payload)
                        except BaseException as e:
                            raise _DecodeFailure(e) from e
                        stats["decode"] += time.perf_counter() - t0
                        stats["read"] += 1
                        off += 1
                        flush()
                        if not _put(q, (sample, li, off), stop):
                            return False
                        if stop.is_set():
                            return False
                    return True

                try:
                    alive = policy.run(read_file)
                except _DecodeFailure as e:
                    raise e.error       # code bug: surface, never skip
                except OSError as e:
                    # retries exhausted (or a fatal errno like EACCES):
                    # degrade, never die — skip THIS file loudly and
                    # keep streaming the rest of the plan
                    rec.inc("data/files_skipped")
                    rec.emit_record(
                        "health_event", condition="data_file_skipped",
                        step=None, metric="data/files_skipped",
                        value=float(fi), threshold=None, action="skip")
                    print(f"[data] worker {w}: skipping shard "
                          f"{self.paths[int(fi)]} after retries "
                          f"({e!r}); this epoch is degraded by that "
                          "file's remaining records", flush=True)
                    continue
                if not alive:
                    return
            _put(q, _END, stop)
        except BaseException as e:      # surfaced at the consumer
            _put(q, _RaiseItem(e), stop)
        finally:
            flush(force=True)

    def _record_rng(self, epoch: int, file_index: int,
                    record_index: int) -> np.random.RandomState:
        """Stateless per-record RNG: nothing to checkpoint, and a resumed
        record sees the SAME stream the uninterrupted run gave it."""
        return np.random.RandomState(
            (self.seed * 1000003 + epoch * 8191 + file_index * 131071
             + record_index * 7 + 5) % (2 ** 31 - 1))

    def _stage_loop(self, worker_qs, plans, rr0: int, epoch: int, outq,
                    stop, train: bool):
        """Deterministic round-robin batcher + device stager: drains the
        worker queues in plan order (sample order is a function of the
        plan alone), collates owned batches, runs ``place_fn`` ahead of
        the consumer, and attaches an O(n_workers) cursor snapshot —
        per-worker ``(plan_pos, next_offset)`` against the shared,
        never-mutated plan; the full ``workers`` lists materialize only
        when a checkpoint asks (:meth:`_cursor_dict`)."""
        rec = self._rec()
        n = len(worker_qs)
        # pos[w]: None = nothing consumed (full plan remains),
        # (li, off) = last consumed sample's plan entry + next record,
        # _WEND = stream drained
        pos: List = [None] * n
        active = [True] * n
        rr = rr0 % max(n, 1)
        buf = []

        def emit(batch_samples):
            host = self.collate(batch_samples)
            if rec.enabled:
                rec.inc("data/h2d_bytes", _host_nbytes(host))
            placed = host if self.place_fn is None else self.place_fn(host)
            return _put(outq, (placed, (tuple(pos), rr)), stop)

        try:
            while any(active):
                if not active[rr]:
                    rr = (rr + 1) % n
                    continue
                item = _get(worker_qs[rr], stop)
                if item is _STOPPED:
                    return
                if item is _END:
                    active[rr] = False
                    pos[rr] = _WEND
                    rr = (rr + 1) % n
                    continue
                if isinstance(item, _RaiseItem):
                    _put(outq, item, stop)
                    return
                sample, li, off = item
                pos[rr] = (li, off)
                buf.append(sample)
                rr = (rr + 1) % n
                if len(buf) == self.batch_size:
                    if not emit(buf):
                        return
                    buf = []
            if buf and not self.drop_last:
                if not emit(buf):
                    return
            _put(outq, _END, stop)
        except BaseException as e:
            _put(outq, _RaiseItem(e), stop)
