"""CIFAR-10 loader (≙ models/resnet/Utils.scala Cifar10DataSet's local file
path + pyspark dataset conventions).

Reads the python-pickle batches or the binary format from a local dir;
falls back to deterministic synthetic data (zero-egress environment).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

# ≙ models/resnet/Utils.scala trainMean/trainStd (BGR order)
TRAIN_MEAN = (125.3, 123.0, 113.9)
TRAIN_STD = (63.0, 62.1, 66.7)


def _load_py_batch(path):
    with open(path, "rb") as f:
        d = pickle.load(f, encoding="bytes")
    x = d[b"data"].reshape(-1, 3, 32, 32)
    y = np.asarray(d[b"labels"], np.uint8)
    return x, y


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, n).astype(np.uint8)
    x = (rng.rand(n, 3, 32, 32) * 60).astype(np.uint8)
    for c in range(10):
        x[labels == c, c % 3, 4 + 2 * (c // 3):10 + 2 * (c // 3), :] = 200
    return x, labels


def read_data_sets(data_dir, data_type="train"):
    """Returns (images [N,3,32,32] uint8 RGB, labels [N] uint8 0-based)."""
    batch_dir = os.path.join(data_dir, "cifar-10-batches-py")
    if os.path.isdir(batch_dir):
        if data_type == "train":
            parts = [_load_py_batch(os.path.join(batch_dir, f"data_batch_{i}"))
                     for i in range(1, 6)]
            return (np.concatenate([p[0] for p in parts]),
                    np.concatenate([p[1] for p in parts]))
        return _load_py_batch(os.path.join(batch_dir, "test_batch"))
    n = 2048 if data_type == "train" else 512
    return _synthetic(n, seed=0 if data_type == "train" else 1)


def load_data(data_dir="/tmp/cifar10"):
    xtr, ytr = read_data_sets(data_dir, "train")
    xte, yte = read_data_sets(data_dir, "test")
    return (xtr, ytr), (xte, yte)
