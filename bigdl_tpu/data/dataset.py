"""DataSet abstractions (≙ dataset/DataSet.scala, Transformer.scala).

A DataSet yields batches (MiniBatch or (x, y) arrays).  Transformers compose
with ``->`` like the reference (`dataset -> transformer`).  LocalDataSet
shuffles/iterates host-side numpy; DistributedDataSet shards per mesh
data-parallel group (the Spark-RDD partitioning analogue: each dp shard of
the global batch is produced on its host and laid out on its mesh slice).
"""
from __future__ import annotations

import math
from typing import Callable, Iterable, Iterator, List, Optional

import numpy as np

from .minibatch import MiniBatch, Sample, samples_to_minibatch, PaddingParam


class Transformer:
    """Composable iterator transform (≙ dataset/Transformer.scala)."""

    def apply_iter(self, it: Iterator) -> Iterator:
        raise NotImplementedError

    def __call__(self, it: Iterable) -> Iterator:
        return self.apply_iter(iter(it))

    def __gt__(self, other):
        raise TypeError("use `a -> b` spelled as a.then(b) or a >> b")

    def __rshift__(self, other: "Transformer") -> "Transformer":
        return ChainedTransformer(self, other)

    def then(self, other: "Transformer") -> "Transformer":
        return ChainedTransformer(self, other)


class ChainedTransformer(Transformer):
    def __init__(self, first, second):
        self.first = first
        self.second = second

    def apply_iter(self, it):
        return self.second.apply_iter(self.first.apply_iter(it))


class SampleToMiniBatch(Transformer):
    """≙ dataset/SampleToMiniBatch.scala; drops no samples — last partial
    batch is emitted unless drop_last."""

    def __init__(self, batch_size, feature_padding: Optional[PaddingParam] = None,
                 label_padding: Optional[PaddingParam] = None, drop_last=False):
        self.batch_size = batch_size
        self.feature_padding = feature_padding
        self.label_padding = label_padding
        self.drop_last = drop_last

    def apply_iter(self, it):
        buf: List[Sample] = []
        for s in it:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield samples_to_minibatch(buf, self.feature_padding,
                                           self.label_padding)
                buf = []
        if buf and not self.drop_last:
            yield samples_to_minibatch(buf, self.feature_padding,
                                       self.label_padding)


class FunctionTransformer(Transformer):
    def __init__(self, fn: Callable):
        self.fn = fn

    def apply_iter(self, it):
        for x in it:
            yield self.fn(x)


class DataSet:
    """Base dataset (≙ dataset/DataSet.scala AbstractDataSet)."""

    def size(self) -> int:
        raise NotImplementedError

    def shuffle(self):
        return self

    def data(self, train: bool) -> Iterator:
        raise NotImplementedError

    def transform(self, transformer: Transformer) -> "TransformedDataSet":
        return TransformedDataSet(self, transformer)

    # reference spelling: dataset -> transformer
    def __rshift__(self, transformer):
        return self.transform(transformer)

    @staticmethod
    def array(samples, batch_size=None, shuffle=True):
        ds = LocalArrayDataSet(samples, shuffle=shuffle)
        if batch_size is not None:
            return ds.transform(SampleToMiniBatch(batch_size))
        return ds

    @staticmethod
    def minibatch_arrays(x, y, batch_size, shuffle=True, drop_last=True,
                         seed=0):
        return ArrayMiniBatchDataSet(x, y, batch_size, shuffle=shuffle,
                                     drop_last=drop_last, seed=seed)


class LocalArrayDataSet(DataSet):
    """In-memory list of Samples (≙ LocalArrayDataSet in DataSet.scala)."""

    def __init__(self, samples, shuffle=True, seed=0):
        self.samples = list(samples)
        self._shuffle = shuffle
        self._seed = seed
        self._rng = np.random.RandomState(seed)

    def size(self):
        return len(self.samples)

    def shuffle(self):
        self._rng.shuffle(self.samples)
        return self

    def data(self, train=True, epoch=None):
        idx = np.arange(len(self.samples))
        if train and self._shuffle:
            # epoch-seeded permutation (stateless) enables exact mid-epoch
            # resume: the same (seed, epoch) always yields the same order
            rng = self._rng if epoch is None else \
                np.random.RandomState((self._seed * 1000003 + epoch)
                                      % (2 ** 31 - 1))
            rng.shuffle(idx)
        for i in idx:
            yield self.samples[i]


class ArrayMiniBatchDataSet(DataSet):
    """Dense (x, y) arrays batched without per-sample python overhead —
    the fast path feeding the TPU."""

    def __init__(self, x, y, batch_size, shuffle=True, drop_last=True, seed=0):
        self.x = np.asarray(x)
        self.y = None if y is None else np.asarray(y)
        self.batch_size = batch_size
        self._shuffle = shuffle
        self.drop_last = drop_last
        self._seed = seed
        self._rng = np.random.RandomState(seed)

    def size(self):
        return self.x.shape[0]

    def batches_per_epoch(self):
        n = self.x.shape[0] // self.batch_size
        if not self.drop_last and self.x.shape[0] % self.batch_size:
            n += 1
        return n

    def data(self, train=True, epoch=None):
        n = self.x.shape[0]
        idx = np.arange(n)
        if train and self._shuffle:
            rng = self._rng if epoch is None else \
                np.random.RandomState((self._seed * 1000003 + epoch)
                                      % (2 ** 31 - 1))
            rng.shuffle(idx)
        end = n - (n % self.batch_size) if self.drop_last else n
        for start in range(0, end, self.batch_size):
            sel = idx[start:start + self.batch_size]
            xb = self.x[sel]
            yb = None if self.y is None else self.y[sel]
            yield MiniBatch(xb, yb)


class TransformedDataSet(DataSet):
    def __init__(self, base: DataSet, transformer: Transformer):
        self.base = base
        self.transformer = transformer

    def size(self):
        return self.base.size()

    def shuffle(self):
        self.base.shuffle()
        return self

    def data(self, train=True, epoch=None):
        try:
            it = self.base.data(train, epoch=epoch)
        except TypeError:
            it = self.base.data(train)
        return self.transformer.apply_iter(it)

    def batches_per_epoch(self):
        if hasattr(self.transformer, "batch_size"):
            return math.ceil(self.base.size() / self.transformer.batch_size)
        if hasattr(self.base, "batches_per_epoch"):
            return self.base.batches_per_epoch()
        return None


class DistributedDataSet(DataSet):
    """Mesh-sharded dataset (≙ DistributedDataSet over Spark RDDs).

    Wraps a global dataset; `data()` yields global batches whose leading dim
    is divisible by the dp shard count.  Device placement onto the mesh is
    done by DistriOptimizer via jax.device_put with the batch sharding; in a
    multi-host pod each host feeds only its addressable shard
    (process_index-strided slice), mirroring one Spark partition per
    executor.
    """

    def __init__(self, base: DataSet, num_shards: int, shard_index: int = 0):
        self.base = base
        self.num_shards = num_shards
        self.shard_index = shard_index

    def size(self):
        return self.base.size()

    def shuffle(self):
        self.base.shuffle()
        return self

    def batches_per_epoch(self):
        return getattr(self.base, "batches_per_epoch", lambda: None)()

    def data(self, train=True, epoch=None):
        try:
            it = self.base.data(train, epoch=epoch)
        except TypeError:
            it = self.base.data(train)
        for mb in it:
            if mb.size() % self.num_shards:
                # truncate so every shard receives an equal, static shape
                keep = mb.size() - (mb.size() % self.num_shards)
                if keep == 0:
                    continue
                mb = mb.slice(1, keep)
            yield mb
