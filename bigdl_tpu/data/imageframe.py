"""ImageFrame / ImageFeature vision pipeline (≙ transform/vision/image/:
ImageFeature.scala, ImageFrame.scala, FeatureTransformer.scala +
augmentation/*.scala: Resize, Brightness, Contrast, Saturation, Hue,
ChannelNormalize, ChannelScaledNormalizer, ChannelOrder, Crop (Center/
Random/Fixed), Expand, Filler, HFlip, PixelNormalizer, RandomCropper,
RandomResize, RandomTransformer, ColorJitter).

The reference wraps OpenCV Mats; here an ImageFeature carries an HWC
float32 numpy image (BGR, [0,255]) plus metadata, all transforms are pure
numpy on the host, and `to_sample`/`to_batch` hand contiguous CHW arrays to
the TPU feed.  No OpenCV dependency: resize/hue run on numpy (PIL assists
file decoding only).
"""
from __future__ import annotations

import os
from typing import Callable, Iterable, List, Optional, Sequence

import numpy as np

from .dataset import DataSet, Transformer as _IterTransformer
from .minibatch import MiniBatch, Sample


class ImageFeature(dict):
    """Keyed feature store for one image (≙ ImageFeature.scala)."""

    IMAGE = "floats"          # HWC float32 BGR
    BYTES = "bytes"
    URI = "uri"
    LABEL = "label"
    ORIGINAL_SIZE = "originalSize"
    SAMPLE = "sample"
    PREDICT = "predict"
    BOUNDING_BOX = "boundingBox"

    def __init__(self, image=None, label=None, uri=None, **kw):
        super().__init__(**kw)
        if image is not None:
            self[self.IMAGE] = np.asarray(image, np.float32)
            self[self.ORIGINAL_SIZE] = tuple(self[self.IMAGE].shape)
        if label is not None:
            self[self.LABEL] = label
        if uri is not None:
            self[self.URI] = uri

    @property
    def image(self) -> np.ndarray:
        return self[self.IMAGE]

    @image.setter
    def image(self, v):
        self[self.IMAGE] = np.asarray(v, np.float32)

    @property
    def label(self):
        return self.get(self.LABEL)

    def get_size(self):
        return tuple(self[self.IMAGE].shape)

    def width(self):
        return self[self.IMAGE].shape[1]

    def height(self):
        return self[self.IMAGE].shape[0]


class FeatureTransformer:
    """Per-feature transform, composable with ``>>``
    (≙ FeatureTransformer.scala; `transform` ≙ transformMat)."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        raise NotImplementedError(type(self).__name__)

    def __call__(self, frame_or_feature):
        if isinstance(frame_or_feature, ImageFeature):
            return self.transform(frame_or_feature)
        return frame_or_feature.transform(self)

    def __rshift__(self, other: "FeatureTransformer") -> "FeatureTransformer":
        return ChainedFeatureTransformer(self, other)

    def apply_iter(self, it):
        for f in it:
            yield self.transform(f)


class ChainedFeatureTransformer(FeatureTransformer):
    def __init__(self, *stages):
        self.stages = list(stages)

    def transform(self, feature):
        for s in self.stages:
            feature = s.transform(feature)
        return feature


class PipelineStep(FeatureTransformer):
    """Wrap a plain fn(HWC array) -> HWC array."""

    def __init__(self, fn: Callable[[np.ndarray], np.ndarray]):
        self.fn = fn

    def transform(self, feature):
        feature.image = self.fn(feature.image)
        return feature


# --------------------------------------------------------------------- #
# ImageFrame                                                            #
# --------------------------------------------------------------------- #
class ImageFrame:
    """Collection of ImageFeatures (≙ ImageFrame.scala LocalImageFrame;
    the distributed variant shards by dp rank via DistributedDataSet)."""

    def __init__(self, features: Iterable[ImageFeature]):
        self.features: List[ImageFeature] = list(features)

    # constructors (≙ ImageFrame.read / ImageFrame.array)
    @staticmethod
    def read(path: str, scale_to: Optional[int] = None) -> "ImageFrame":
        from PIL import Image
        paths = []
        if os.path.isdir(path):
            for f in sorted(os.listdir(path)):
                if f.lower().endswith((".jpg", ".jpeg", ".png", ".bmp")):
                    paths.append(os.path.join(path, f))
        else:
            paths = [path]
        feats = []
        for p in paths:
            img = Image.open(p).convert("RGB")
            if scale_to:
                img = img.resize((scale_to, scale_to), Image.BILINEAR)
            arr = np.asarray(img)[..., ::-1].astype(np.float32)
            feats.append(ImageFeature(arr, uri=p))
        return ImageFrame(feats)

    @staticmethod
    def array(images: Sequence[np.ndarray], labels=None) -> "ImageFrame":
        labels = labels if labels is not None else [None] * len(images)
        return ImageFrame(ImageFeature(im, label=lb)
                          for im, lb in zip(images, labels))

    def transform(self, transformer: FeatureTransformer) -> "ImageFrame":
        self.features = [transformer.transform(f) for f in self.features]
        return self

    __rshift__ = transform

    def __len__(self):
        return len(self.features)

    def __iter__(self):
        return iter(self.features)

    def to_samples(self) -> List[Sample]:
        missing = sum(ImageFeature.SAMPLE not in f for f in self.features)
        if missing:
            raise ValueError(
                f"{missing}/{len(self.features)} ImageFeatures have no "
                "prepared 'sample' — run an ImageFrameToSample (after "
                "MatToTensor) transform on the frame first, or use "
                "model.predict_image(frame) which handles raw images")
        return [f[ImageFeature.SAMPLE] for f in self.features]

    def to_dataset(self, batch_size: int, shuffle: bool = True) -> DataSet:
        from .dataset import SampleToMiniBatch
        return (DataSet.array(self.to_samples(), shuffle=shuffle)
                .transform(SampleToMiniBatch(batch_size)))


# --------------------------------------------------------------------- #
# geometry                                                              #
# --------------------------------------------------------------------- #
def _resize_bilinear(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Pure-numpy separable bilinear resize (align_corners=False, the
    OpenCV INTER_LINEAR convention the reference uses)."""
    h, w = img.shape[:2]
    if (h, w) == (out_h, out_w):
        return img
    ys = (np.arange(out_h, dtype=np.float32) + 0.5) * (h / out_h) - 0.5
    xs = (np.arange(out_w, dtype=np.float32) + 0.5) * (w / out_w) - 0.5
    y0 = np.clip(np.floor(ys).astype(np.int64), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(np.int64), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0).astype(np.float32)
    wx = np.clip(xs - x0, 0.0, 1.0).astype(np.float32)
    top = img[y0][:, x0] * (1 - wx)[None, :, None] \
        + img[y0][:, x1] * wx[None, :, None]
    bot = img[y1][:, x0] * (1 - wx)[None, :, None] \
        + img[y1][:, x1] * wx[None, :, None]
    return top * (1 - wy)[:, None, None] + bot * wy[:, None, None]


class Resize(FeatureTransformer):
    """≙ augmentation/Resize.scala."""

    def __init__(self, resize_h: int, resize_w: int):
        self.h, self.w = resize_h, resize_w

    def transform(self, feature):
        img = feature.image
        squeeze = img.ndim == 2
        if squeeze:
            img = img[..., None]
        img = _resize_bilinear(img, self.h, self.w)
        feature.image = img[..., 0] if squeeze else img
        return feature


class AspectScale(FeatureTransformer):
    """Resize the short edge to `min_size`, keeping aspect ratio and capping
    the long edge (≙ augmentation/Resize.scala AspectScale)."""

    def __init__(self, min_size: int, max_size: int = 1000):
        self.min_size, self.max_size = min_size, max_size

    def transform(self, feature):
        h, w = feature.image.shape[:2]
        short, long = min(h, w), max(h, w)
        scale = min(self.min_size / short, self.max_size / long)
        feature.image = _resize_bilinear(
            feature.image, int(round(h * scale)), int(round(w * scale)))
        return feature


class RandomResize(FeatureTransformer):
    """Resize to a size drawn from [min_size, max_size]
    (≙ augmentation/RandomResize.scala)."""

    def __init__(self, min_size: int, max_size: int, seed: int = 0):
        self.min_size, self.max_size = min_size, max_size
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        s = int(self._rng.randint(self.min_size, self.max_size + 1))
        feature.image = _resize_bilinear(feature.image, s, s)
        return feature


class CenterCrop(FeatureTransformer):
    """≙ augmentation/Crop.scala CenterCrop."""

    def __init__(self, crop_width: int, crop_height: int):
        self.cw, self.ch = crop_width, crop_height

    def transform(self, feature):
        h, w = feature.image.shape[:2]
        y0, x0 = (h - self.ch) // 2, (w - self.cw) // 2
        feature.image = feature.image[y0:y0 + self.ch, x0:x0 + self.cw]
        return feature


class RandomCrop(FeatureTransformer):
    """≙ augmentation/Crop.scala RandomCrop."""

    def __init__(self, crop_width: int, crop_height: int, seed: int = 0):
        self.cw, self.ch = crop_width, crop_height
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        h, w = feature.image.shape[:2]
        y0 = int(self._rng.randint(0, h - self.ch + 1))
        x0 = int(self._rng.randint(0, w - self.cw + 1))
        feature.image = feature.image[y0:y0 + self.ch, x0:x0 + self.cw]
        return feature


class FixedCrop(FeatureTransformer):
    """Crop a fixed box; normalized coords if in [0,1]
    (≙ augmentation/Crop.scala FixedCrop)."""

    def __init__(self, x1: float, y1: float, x2: float, y2: float,
                 normalized: bool = False):
        self.box = (x1, y1, x2, y2)
        self.normalized = normalized

    def transform(self, feature):
        h, w = feature.image.shape[:2]
        x1, y1, x2, y2 = self.box
        if self.normalized:
            x1, x2 = x1 * w, x2 * w
            y1, y2 = y1 * h, y2 * h
        feature.image = feature.image[int(y1):int(y2), int(x1):int(x2)]
        return feature


class RandomCropper(FeatureTransformer):
    """Random crop + optional random flip, the ResNet ImageNet train recipe
    (≙ augmentation/RandomCropper.scala)."""

    def __init__(self, crop_width: int, crop_height: int, mirror: bool = True,
                 crop_mode: str = "random", channels: int = 3, seed: int = 0):
        self.cw, self.ch = crop_width, crop_height
        self.mirror = mirror
        self.crop_mode = crop_mode
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        h, w = feature.image.shape[:2]
        if self.crop_mode == "center":
            y0, x0 = (h - self.ch) // 2, (w - self.cw) // 2
        else:
            y0 = int(self._rng.randint(0, h - self.ch + 1))
            x0 = int(self._rng.randint(0, w - self.cw + 1))
        img = feature.image[y0:y0 + self.ch, x0:x0 + self.cw]
        if self.mirror and self._rng.uniform() < 0.5:
            img = img[:, ::-1]
        feature.image = np.ascontiguousarray(img)
        return feature


class RandomAlterAspect(FeatureTransformer):
    """Random scale+aspect-ratio crop resized to a fixed size, the Inception
    training crop (≙ augmentation/RandomAlterAspect.scala)."""

    def __init__(self, min_area_ratio: float = 0.08,
                 max_area_ratio: float = 1.0, min_aspect_ratio: float = 0.75,
                 target_size: int = 224, seed: int = 0):
        self.min_area = min_area_ratio
        self.max_area = max_area_ratio
        self.min_aspect = min_aspect_ratio
        self.target = target_size
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        h, w = feature.image.shape[:2]
        area = h * w
        for _ in range(10):
            t_area = area * self._rng.uniform(self.min_area, self.max_area)
            ar = self._rng.uniform(self.min_aspect, 1.0 / self.min_aspect)
            cw = int(round(np.sqrt(t_area * ar)))
            ch = int(round(np.sqrt(t_area / ar)))
            if cw <= w and ch <= h:
                y0 = int(self._rng.randint(0, h - ch + 1))
                x0 = int(self._rng.randint(0, w - cw + 1))
                crop = feature.image[y0:y0 + ch, x0:x0 + cw]
                feature.image = _resize_bilinear(crop, self.target,
                                                 self.target)
                return feature
        feature.image = _resize_bilinear(feature.image, self.target,
                                         self.target)
        return feature


class Expand(FeatureTransformer):
    """Place the image on a larger mean-filled canvas (SSD-style zoom-out;
    ≙ augmentation/Expand.scala)."""

    def __init__(self, means: Sequence[float] = (123.0, 117.0, 104.0),
                 max_expand_ratio: float = 4.0, seed: int = 0):
        self.means = np.asarray(means, np.float32)
        self.max_ratio = max_expand_ratio
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        ratio = self._rng.uniform(1.0, self.max_ratio)
        h, w, c = feature.image.shape
        nh, nw = int(h * ratio), int(w * ratio)
        y0 = int(self._rng.randint(0, nh - h + 1))
        x0 = int(self._rng.randint(0, nw - w + 1))
        canvas = np.tile(self.means[None, None, :], (nh, nw, 1))
        canvas[y0:y0 + h, x0:x0 + w] = feature.image
        feature.image = canvas.astype(np.float32)
        return feature


class Filler(FeatureTransformer):
    """Fill a (normalized-coord) sub-rectangle with a constant
    (≙ augmentation/Filler.scala)."""

    def __init__(self, start_x: float, start_y: float, end_x: float,
                 end_y: float, value: float = 255.0):
        self.box = (start_x, start_y, end_x, end_y)
        self.value = value

    def transform(self, feature):
        h, w = feature.image.shape[:2]
        x1, y1, x2, y2 = self.box
        feature.image[int(y1 * h):int(y2 * h), int(x1 * w):int(x2 * w)] = \
            self.value
        return feature


class HFlipVision(FeatureTransformer):
    """Unconditional horizontal flip (≙ augmentation/HFlip.scala; wrap in
    RandomTransformer for the probabilistic version)."""

    def transform(self, feature):
        feature.image = np.ascontiguousarray(feature.image[:, ::-1])
        return feature


class RandomTransformer(FeatureTransformer):
    """Apply `inner` with probability p (≙ augmentation/RandomTransformer.scala)."""

    def __init__(self, inner: FeatureTransformer, prob: float, seed: int = 0):
        self.inner = inner
        self.prob = prob
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        if self._rng.uniform() < self.prob:
            feature = self.inner.transform(feature)
        return feature


# --------------------------------------------------------------------- #
# photometric                                                           #
# --------------------------------------------------------------------- #
class Brightness(FeatureTransformer):
    """Add a uniform delta in [delta_low, delta_high]
    (≙ augmentation/Brightness.scala)."""

    def __init__(self, delta_low: float = -32.0, delta_high: float = 32.0,
                 seed: int = 0):
        self.low, self.high = delta_low, delta_high
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        feature.image = feature.image + \
            float(self._rng.uniform(self.low, self.high))
        return feature


class Contrast(FeatureTransformer):
    """Scale by a uniform factor (≙ augmentation/Contrast.scala)."""

    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5,
                 seed: int = 0):
        self.low, self.high = delta_low, delta_high
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        feature.image = feature.image * \
            float(self._rng.uniform(self.low, self.high))
        return feature


class Saturation(FeatureTransformer):
    """Blend with greyscale by a uniform factor
    (≙ augmentation/Saturation.scala)."""

    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5,
                 seed: int = 0):
        self.low, self.high = delta_low, delta_high
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        img = feature.image
        grey = (img[..., 0] * 0.299 + img[..., 1] * 0.587
                + img[..., 2] * 0.114)[..., None]
        alpha = float(self._rng.uniform(self.low, self.high))
        feature.image = img * alpha + grey * (1.0 - alpha)
        return feature


class Hue(FeatureTransformer):
    """Rotate hue by a uniform delta in degrees (≙ augmentation/Hue.scala;
    HSV roundtrip done in numpy instead of OpenCV)."""

    def __init__(self, delta_low: float = -18.0, delta_high: float = 18.0,
                 seed: int = 0):
        self.low, self.high = delta_low, delta_high
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        img = np.clip(feature.image, 0, 255) / 255.0  # BGR
        b, g, r = img[..., 0], img[..., 1], img[..., 2]
        mx = img.max(-1)
        mn = img.min(-1)
        diff = mx - mn + 1e-12
        h = np.zeros_like(mx)
        rmax = mx == r
        gmax = (mx == g) & ~rmax
        bmax = ~(rmax | gmax)
        h[rmax] = (60 * (g - b) / diff)[rmax] % 360
        h[gmax] = (60 * (b - r) / diff + 120)[gmax]
        h[bmax] = (60 * (r - g) / diff + 240)[bmax]
        s = np.where(mx > 0, diff / (mx + 1e-12), 0.0)
        v = mx
        h = (h + float(self._rng.uniform(self.low, self.high))) % 360
        c = v * s
        hp = h / 60.0
        x = c * (1 - np.abs(hp % 2 - 1))
        z = np.zeros_like(c)
        conds = [hp < 1, hp < 2, hp < 3, hp < 4, hp < 5, hp >= 5]
        rgb = np.select(
            [cnd[..., None] for cnd in conds],
            [np.stack([c, x, z], -1), np.stack([x, c, z], -1),
             np.stack([z, c, x], -1), np.stack([z, x, c], -1),
             np.stack([x, z, c], -1), np.stack([c, z, x], -1)])
        rgb = rgb + (v - c)[..., None]
        feature.image = (rgb[..., ::-1] * 255.0).astype(np.float32)
        return feature


class ColorJitterVision(FeatureTransformer):
    """Random-order brightness/contrast/saturation(/hue)
    (≙ augmentation/ColorJitter.scala)."""

    def __init__(self, brightness_prob=0.5, brightness_delta=32.0,
                 contrast_prob=0.5, contrast_lower=0.5, contrast_upper=1.5,
                 saturation_prob=0.5, saturation_lower=0.5,
                 saturation_upper=1.5, hue_prob=0.5, hue_delta=18.0,
                 seed: int = 0):
        rng = np.random.RandomState(seed)
        self._rng = rng
        self.ops = [
            RandomTransformer(Brightness(-brightness_delta, brightness_delta,
                                         seed), brightness_prob, seed),
            RandomTransformer(Contrast(contrast_lower, contrast_upper, seed),
                              contrast_prob, seed),
            RandomTransformer(Saturation(saturation_lower, saturation_upper,
                                         seed), saturation_prob, seed),
            RandomTransformer(Hue(-hue_delta, hue_delta, seed), hue_prob,
                              seed),
        ]

    def transform(self, feature):
        order = np.arange(len(self.ops))
        self._rng.shuffle(order)
        for i in order:
            feature = self.ops[i].transform(feature)
        return feature


# --------------------------------------------------------------------- #
# normalize / layout                                                    #
# --------------------------------------------------------------------- #
class ChannelNormalize(FeatureTransformer):
    """(img - mean) / std per channel (≙ augmentation/ChannelNormalize.scala)."""

    def __init__(self, mean_b: float, mean_g: float, mean_r: float,
                 std_b: float = 1.0, std_g: float = 1.0, std_r: float = 1.0):
        self.mean = np.asarray([mean_b, mean_g, mean_r], np.float32)
        self.std = np.asarray([std_b, std_g, std_r], np.float32)

    def transform(self, feature):
        feature.image = (feature.image - self.mean) / self.std
        return feature


class ChannelScaledNormalizer(FeatureTransformer):
    """Per-channel mean subtraction + global scale
    (≙ augmentation/ChannelScaledNormalizer.scala)."""

    def __init__(self, mean_b: float, mean_g: float, mean_r: float,
                 scale: float):
        self.mean = np.asarray([mean_b, mean_g, mean_r], np.float32)
        self.scale = scale

    def transform(self, feature):
        feature.image = (feature.image - self.mean) * self.scale
        return feature


class PixelNormalizer(FeatureTransformer):
    """Subtract a whole mean image (≙ augmentation/PixelNormalizer.scala)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def transform(self, feature):
        feature.image = feature.image - self.means
        return feature


class ChannelOrder(FeatureTransformer):
    """Swap BGR <-> RGB (≙ augmentation/ChannelOrder.scala)."""

    def transform(self, feature):
        feature.image = np.ascontiguousarray(feature.image[..., ::-1])
        return feature


class MatToTensor(FeatureTransformer):
    """HWC -> CHW contiguous 'tensor' layout (≙ opencv MatToTensor.scala)."""

    def __init__(self, to_rgb: bool = False):
        self.to_rgb = to_rgb

    def transform(self, feature):
        img = feature.image
        if self.to_rgb:
            img = img[..., ::-1]
        feature.image = np.ascontiguousarray(np.transpose(img, (2, 0, 1)))
        return feature


class ImageFrameToSample(FeatureTransformer):
    """Attach Sample(chw, label) to each feature
    (≙ ImageFeatureToSample / convertor in Convertor.scala)."""

    def __init__(self, target_keys: Sequence[str] = ("label",)):
        self.target_keys = target_keys

    def transform(self, feature):
        img = feature.image
        chw = img if img.ndim == 3 and img.shape[0] in (1, 3) \
            else np.transpose(img, (2, 0, 1))
        label = feature.get(ImageFeature.LABEL)
        feature[ImageFeature.SAMPLE] = Sample(
            np.ascontiguousarray(chw, np.float32),
            None if label is None else np.float32(label))
        return feature


# --------------------------------------------------------------------- #
# detection (roi) augmentations  ≙ transform/vision/image/label/roi     #
# --------------------------------------------------------------------- #
def _rois(feature):
    return np.asarray(feature[ImageFeature.BOUNDING_BOX], np.float32)


def _set_rois(feature, rois, keep=None):
    feature[ImageFeature.BOUNDING_BOX] = np.asarray(rois, np.float32)
    if keep is not None:
        label = feature.get(ImageFeature.LABEL)
        if isinstance(label, np.ndarray) and label.shape[:1] == keep.shape:
            feature[ImageFeature.LABEL] = label[keep]
    return feature


class RoiNormalize(FeatureTransformer):
    """Normalize rois (x1,y1,x2,y2 pixels) to [0, 1]
    (≙ roi/RoiNormalize.scala)."""

    def transform(self, feature):
        h, w = feature.image.shape[:2]
        scale = np.array([w, h, w, h], np.float32)
        return _set_rois(feature, _rois(feature) / scale)


class RoiHFlip(FeatureTransformer):
    """Horizontally flip rois; pair with HFlip on the image
    (≙ roi/RoiHFlip.scala)."""

    def __init__(self, normalized=True):
        self.normalized = normalized

    def transform(self, feature):
        rois = _rois(feature)
        width = 1.0 if self.normalized else feature.image.shape[1]
        flipped = rois.copy()
        flipped[:, 0] = width - rois[:, 2]
        flipped[:, 2] = width - rois[:, 0]
        return _set_rois(feature, flipped)


class RoiResize(FeatureTransformer):
    """Rescale pixel rois after an image resize, using the recorded
    originalSize -> current size ratio (≙ roi/RoiResize.scala).
    Normalized rois are resize-invariant, so this is a no-op for them."""

    def __init__(self, normalized=True):
        self.normalized = normalized

    def transform(self, feature):
        if self.normalized:
            return feature
        oh, ow = feature[ImageFeature.ORIGINAL_SIZE][:2]
        h, w = feature.image.shape[:2]
        scale = np.array([w / ow, h / oh, w / ow, h / oh], np.float32)
        return _set_rois(feature, _rois(feature) * scale)


class RoiProject(FeatureTransformer):
    """Clip normalized rois to the image window [0,1], dropping boxes that
    fall outside — or whose center is outside when
    ``need_meet_center_constraint`` (≙ roi/RoiProject.scala)."""

    def __init__(self, need_meet_center_constraint=True):
        self.need_meet_center_constraint = need_meet_center_constraint

    def transform(self, feature):
        rois = _rois(feature)
        if self.need_meet_center_constraint:
            cx = (rois[:, 0] + rois[:, 2]) / 2
            cy = (rois[:, 1] + rois[:, 3]) / 2
            keep = (cx >= 0) & (cx <= 1) & (cy >= 0) & (cy <= 1)
        else:
            keep = (rois[:, 2] > 0) & (rois[:, 0] < 1) \
                & (rois[:, 3] > 0) & (rois[:, 1] < 1)
        clipped = np.clip(rois[keep], 0.0, 1.0)
        return _set_rois(feature, clipped, keep=keep)


def _project_rois_to_window(rois, x1, y1, x2, y2):
    """Re-express normalized rois in a normalized crop window's frame."""
    w, h = max(x2 - x1, 1e-6), max(y2 - y1, 1e-6)
    out = rois.copy()
    out[:, 0] = (rois[:, 0] - x1) / w
    out[:, 2] = (rois[:, 2] - x1) / w
    out[:, 1] = (rois[:, 1] - y1) / h
    out[:, 3] = (rois[:, 3] - y1) / h
    return out


class DetectionCrop(FeatureTransformer):
    """Crop the image to a detection stored at ``roi_key`` ((x1,y1,x2,y2),
    normalized by default) and project rois into the crop
    (≙ DetectionCrop.scala)."""

    def __init__(self, roi_key, normalized=True):
        self.roi_key = roi_key
        self.normalized = normalized

    def transform(self, feature):
        h, w = feature.image.shape[:2]
        roi = np.asarray(feature[self.roi_key], np.float32).reshape(-1)[:4]
        if not self.normalized:
            roi = roi / np.array([w, h, w, h], np.float32)
        x1, y1, x2, y2 = np.clip(roi, 0.0, 1.0)
        # degenerate/out-of-image detections clamp to a 1px valid window
        px1 = min(int(x1 * w), w - 1)
        py1 = min(int(y1 * h), h - 1)
        px2 = min(max(int(x2 * w), px1 + 1), w)
        py2 = min(max(int(y2 * h), py1 + 1), h)
        feature.image = feature.image[py1:py2, px1:px2]
        if ImageFeature.BOUNDING_BOX in feature:
            rois = _project_rois_to_window(_rois(feature), x1, y1, x2, y2)
            _set_rois(feature, rois)
        return feature


class RandomSampler(FeatureTransformer):
    """SSD training crop sampler (≙ RandomSampler.scala): pick a random
    min-IoU constraint from {none, .1, .3, .5, .7, .9, full}; sample up to
    ``max_trials`` crops (scale in [0.3, 1], aspect in [0.5, 2]) until one
    satisfies it w.r.t. the ground-truth rois; crop, project rois into the
    window, and drop boxes whose center left the crop."""

    MIN_IOUS = (None, 0.1, 0.3, 0.5, 0.7, 0.9, "all")

    def __init__(self, max_trials=50, seed=None):
        self.max_trials = max_trials
        self._rng = np.random.RandomState(seed)

    @staticmethod
    def _iou(rois, window):
        x1 = np.maximum(rois[:, 0], window[0])
        y1 = np.maximum(rois[:, 1], window[1])
        x2 = np.minimum(rois[:, 2], window[2])
        y2 = np.minimum(rois[:, 3], window[3])
        inter = np.clip(x2 - x1, 0, None) * np.clip(y2 - y1, 0, None)
        area_r = (rois[:, 2] - rois[:, 0]) * (rois[:, 3] - rois[:, 1])
        area_w = (window[2] - window[0]) * (window[3] - window[1])
        return inter / np.maximum(area_r + area_w - inter, 1e-12)

    def transform(self, feature):
        choice = self.MIN_IOUS[self._rng.randint(len(self.MIN_IOUS))]
        if choice == "all":
            return feature
        rois = _rois(feature) if ImageFeature.BOUNDING_BOX in feature \
            else np.zeros((0, 4), np.float32)
        for _ in range(self.max_trials):
            scale = self._rng.uniform(0.3, 1.0)
            ratio = self._rng.uniform(max(0.5, scale * scale),
                                      min(2.0, 1.0 / (scale * scale)))
            cw = scale * np.sqrt(ratio)
            ch = scale / np.sqrt(ratio)
            if cw > 1.0 or ch > 1.0:
                continue
            cx1 = self._rng.uniform(0, 1.0 - cw)
            cy1 = self._rng.uniform(0, 1.0 - ch)
            window = (cx1, cy1, cx1 + cw, cy1 + ch)
            if choice is not None and len(rois) \
                    and self._iou(rois, window).max() < choice:
                continue
            crop = DetectionCrop("_sampler_roi")
            feature["_sampler_roi"] = np.array(window, np.float32)
            feature = crop.transform(feature)
            del feature["_sampler_roi"]
            if ImageFeature.BOUNDING_BOX in feature:
                feature = RoiProject(True).transform(feature)
            return feature
        return feature


class RandomAspectScale(FeatureTransformer):
    """Aspect-preserving resize with the shorter side drawn from
    ``scales``; the longer side is capped at ``max_size`` and both dims
    rounded down to multiples of ``scale_multiple_of``
    (≙ RandomAspectScale.scala)."""

    def __init__(self, scales, scale_multiple_of=1, max_size=1000,
                 seed=None):
        self.scales = list(scales)
        self.scale_multiple_of = scale_multiple_of
        self.max_size = max_size
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        h, w = feature.image.shape[:2]
        target = self.scales[self._rng.randint(len(self.scales))]
        scale = target / min(h, w)
        if scale * max(h, w) > self.max_size:
            scale = self.max_size / max(h, w)
        nh, nw = int(h * scale), int(w * scale)
        m = self.scale_multiple_of
        nh, nw = max(nh // m * m, m), max(nw // m * m, m)
        feature.image = _resize_bilinear(feature.image, nh, nw)
        return feature


# --------------------------------------------------------------------- #
# byte decoders + pyspark-name aliases                                  #
# --------------------------------------------------------------------- #
class BytesToMat(FeatureTransformer):
    """Decode an encoded image byte string at ``byte_key`` into the float
    HWC image (≙ BytesToMat.scala; PIL replaces OpenCV)."""

    def __init__(self, byte_key=ImageFeature.BYTES):
        self.byte_key = byte_key

    def transform(self, feature):
        import io
        from PIL import Image
        img = Image.open(io.BytesIO(feature[self.byte_key])).convert("RGB")
        arr = np.asarray(img, np.float32)[:, :, ::-1]    # BGR convention
        feature.image = arr
        feature[ImageFeature.ORIGINAL_SIZE] = tuple(arr.shape)
        return feature


class PixelBytesToMat(FeatureTransformer):
    """Raw HWC uint8 pixel bytes -> float image, using the recorded
    originalSize (≙ PixelBytesToMat.scala)."""

    def __init__(self, byte_key=ImageFeature.BYTES):
        self.byte_key = byte_key

    def transform(self, feature):
        shape = feature[ImageFeature.ORIGINAL_SIZE]
        arr = np.frombuffer(feature[self.byte_key],
                            np.uint8).reshape(shape)
        feature.image = arr.astype(np.float32)
        return feature


class MatToFloats(FeatureTransformer):
    """Ensure the image is a float32 HWC array at ``out_key``; invalid /
    missing images become zeros of the valid_* dims
    (≙ MatToFloats.scala)."""

    def __init__(self, valid_height=300, valid_width=300, valid_channel=3,
                 out_key=ImageFeature.IMAGE):
        self.valid = (valid_height, valid_width, valid_channel)
        self.out_key = out_key

    def transform(self, feature):
        img = feature.get(ImageFeature.IMAGE)
        if img is None or np.size(img) == 0:
            img = np.zeros(self.valid, np.float32)
        feature[self.out_key] = np.asarray(img, np.float32)
        return feature


class Pipeline(ChainedFeatureTransformer):
    """pyspark spelling: Pipeline([t1, t2, ...])."""

    def __init__(self, transformers):
        super().__init__(*transformers)


# name-compat aliases (pyspark transform/vision/image.py spellings; the
# *Vision suffix avoids clashing with data.image's batch-pipeline ops)
HFlip = HFlipVision
ColorJitter = ColorJitterVision
PixelNormalize = PixelNormalizer
LocalImageFrame = ImageFrame


class DistributedImageFrame(ImageFrame):
    """Single-process stand-in for the Spark-RDD variant: same API; on a
    mesh the DataSet layer shards features by dp rank."""


class FixExpand(FeatureTransformer):
    """Expand the canvas to (expand_height, expand_width), centering the
    original image on zeros (≙ FixExpand.scala)."""

    def __init__(self, expand_height, expand_width):
        self.eh, self.ew = int(expand_height), int(expand_width)

    def transform(self, feature):
        img = feature.image
        h, w, c = img.shape
        if self.eh < h or self.ew < w:
            raise ValueError(f"FixExpand target ({self.eh},{self.ew}) is "
                             f"smaller than the image ({h},{w})")
        out = np.zeros((self.eh, self.ew, c), img.dtype)
        y0 = (self.eh - h) // 2
        x0 = (self.ew - w) // 2
        out[y0:y0 + h, x0:x0 + w] = img
        feature.image = out
        return feature


class SeqFileFolder:
    """Read Hadoop SequenceFile image shards into an ImageFrame
    (≙ SeqFileFolder.scala files_to_image_frame; utils/seqfile.py does
    the wire format)."""

    @classmethod
    def files_to_image_frame(cls, url, class_num=None):
        import glob
        import math
        import os
        from ..utils.seqfile import SequenceFileReader
        feats = []
        if os.path.isdir(url):
            paths = sorted(set(glob.glob(os.path.join(url, "*.seq"))
                               + glob.glob(os.path.join(url, "part-*"))))
            if not paths:
                raise FileNotFoundError(
                    f"{url}: no *.seq or part-* SequenceFile shards found")
        else:
            paths = [url]
        for p in paths:
            for key, value in SequenceFileReader(p):
                f = ImageFeature()
                f[ImageFeature.URI] = key.decode("utf-8", "replace") \
                    if isinstance(key, bytes) else str(key)
                f[ImageFeature.BYTES] = value
                # reference imagenet shards encode "<label>\n<uri>" keys:
                # the LEADING token is the label when numeric and finite
                tokens = f[ImageFeature.URI].replace("\n", " ").split()
                if tokens:
                    try:
                        label = float(tokens[0])
                        if math.isfinite(label):
                            if class_num is not None and not \
                                    1 <= label <= class_num:
                                raise ValueError(
                                    f"{p}: label {label} outside "
                                    f"[1, {class_num}] for key "
                                    f"{f[ImageFeature.URI]!r}")
                            f[ImageFeature.LABEL] = label
                    except ValueError as e:
                        if "outside" in str(e):
                            raise
                feats.append(f)
        return ImageFrame(feats)
