"""ImageFrame / ImageFeature vision pipeline (≙ transform/vision/image/:
ImageFeature.scala, ImageFrame.scala, FeatureTransformer.scala +
augmentation/*.scala: Resize, Brightness, Contrast, Saturation, Hue,
ChannelNormalize, ChannelScaledNormalizer, ChannelOrder, Crop (Center/
Random/Fixed), Expand, Filler, HFlip, PixelNormalizer, RandomCropper,
RandomResize, RandomTransformer, ColorJitter).

The reference wraps OpenCV Mats; here an ImageFeature carries an HWC
float32 numpy image (BGR, [0,255]) plus metadata, all transforms are pure
numpy on the host, and `to_sample`/`to_batch` hand contiguous CHW arrays to
the TPU feed.  No OpenCV dependency: resize/hue run on numpy (PIL assists
file decoding only).
"""
from __future__ import annotations

import os
from typing import Callable, Iterable, List, Optional, Sequence

import numpy as np

from .dataset import DataSet, Transformer as _IterTransformer
from .minibatch import MiniBatch, Sample


class ImageFeature(dict):
    """Keyed feature store for one image (≙ ImageFeature.scala)."""

    IMAGE = "floats"          # HWC float32 BGR
    BYTES = "bytes"
    URI = "uri"
    LABEL = "label"
    ORIGINAL_SIZE = "originalSize"
    SAMPLE = "sample"
    PREDICT = "predict"
    BOUNDING_BOX = "boundingBox"

    def __init__(self, image=None, label=None, uri=None, **kw):
        super().__init__(**kw)
        if image is not None:
            self[self.IMAGE] = np.asarray(image, np.float32)
            self[self.ORIGINAL_SIZE] = tuple(self[self.IMAGE].shape)
        if label is not None:
            self[self.LABEL] = label
        if uri is not None:
            self[self.URI] = uri

    @property
    def image(self) -> np.ndarray:
        return self[self.IMAGE]

    @image.setter
    def image(self, v):
        self[self.IMAGE] = np.asarray(v, np.float32)

    @property
    def label(self):
        return self.get(self.LABEL)

    def get_size(self):
        return tuple(self[self.IMAGE].shape)

    def width(self):
        return self[self.IMAGE].shape[1]

    def height(self):
        return self[self.IMAGE].shape[0]


class FeatureTransformer:
    """Per-feature transform, composable with ``>>``
    (≙ FeatureTransformer.scala; `transform` ≙ transformMat)."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        raise NotImplementedError(type(self).__name__)

    def __call__(self, frame_or_feature):
        if isinstance(frame_or_feature, ImageFeature):
            return self.transform(frame_or_feature)
        return frame_or_feature.transform(self)

    def __rshift__(self, other: "FeatureTransformer") -> "FeatureTransformer":
        return ChainedFeatureTransformer(self, other)

    def apply_iter(self, it):
        for f in it:
            yield self.transform(f)


class ChainedFeatureTransformer(FeatureTransformer):
    def __init__(self, *stages):
        self.stages = list(stages)

    def transform(self, feature):
        for s in self.stages:
            feature = s.transform(feature)
        return feature


class PipelineStep(FeatureTransformer):
    """Wrap a plain fn(HWC array) -> HWC array."""

    def __init__(self, fn: Callable[[np.ndarray], np.ndarray]):
        self.fn = fn

    def transform(self, feature):
        feature.image = self.fn(feature.image)
        return feature


# --------------------------------------------------------------------- #
# ImageFrame                                                            #
# --------------------------------------------------------------------- #
class ImageFrame:
    """Collection of ImageFeatures (≙ ImageFrame.scala LocalImageFrame;
    the distributed variant shards by dp rank via DistributedDataSet)."""

    def __init__(self, features: Iterable[ImageFeature]):
        self.features: List[ImageFeature] = list(features)

    # constructors (≙ ImageFrame.read / ImageFrame.array)
    @staticmethod
    def read(path: str, scale_to: Optional[int] = None) -> "ImageFrame":
        from PIL import Image
        paths = []
        if os.path.isdir(path):
            for f in sorted(os.listdir(path)):
                if f.lower().endswith((".jpg", ".jpeg", ".png", ".bmp")):
                    paths.append(os.path.join(path, f))
        else:
            paths = [path]
        feats = []
        for p in paths:
            img = Image.open(p).convert("RGB")
            if scale_to:
                img = img.resize((scale_to, scale_to), Image.BILINEAR)
            arr = np.asarray(img)[..., ::-1].astype(np.float32)
            feats.append(ImageFeature(arr, uri=p))
        return ImageFrame(feats)

    @staticmethod
    def array(images: Sequence[np.ndarray], labels=None) -> "ImageFrame":
        labels = labels if labels is not None else [None] * len(images)
        return ImageFrame(ImageFeature(im, label=lb)
                          for im, lb in zip(images, labels))

    def transform(self, transformer: FeatureTransformer) -> "ImageFrame":
        self.features = [transformer.transform(f) for f in self.features]
        return self

    __rshift__ = transform

    def __len__(self):
        return len(self.features)

    def __iter__(self):
        return iter(self.features)

    def to_samples(self) -> List[Sample]:
        return [f[ImageFeature.SAMPLE] for f in self.features]

    def to_dataset(self, batch_size: int, shuffle: bool = True) -> DataSet:
        from .dataset import SampleToMiniBatch
        return (DataSet.array(self.to_samples(), shuffle=shuffle)
                .transform(SampleToMiniBatch(batch_size)))


# --------------------------------------------------------------------- #
# geometry                                                              #
# --------------------------------------------------------------------- #
def _resize_bilinear(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Pure-numpy separable bilinear resize (align_corners=False, the
    OpenCV INTER_LINEAR convention the reference uses)."""
    h, w = img.shape[:2]
    if (h, w) == (out_h, out_w):
        return img
    ys = (np.arange(out_h, dtype=np.float32) + 0.5) * (h / out_h) - 0.5
    xs = (np.arange(out_w, dtype=np.float32) + 0.5) * (w / out_w) - 0.5
    y0 = np.clip(np.floor(ys).astype(np.int64), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(np.int64), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0).astype(np.float32)
    wx = np.clip(xs - x0, 0.0, 1.0).astype(np.float32)
    top = img[y0][:, x0] * (1 - wx)[None, :, None] \
        + img[y0][:, x1] * wx[None, :, None]
    bot = img[y1][:, x0] * (1 - wx)[None, :, None] \
        + img[y1][:, x1] * wx[None, :, None]
    return top * (1 - wy)[:, None, None] + bot * wy[:, None, None]


class Resize(FeatureTransformer):
    """≙ augmentation/Resize.scala."""

    def __init__(self, resize_h: int, resize_w: int):
        self.h, self.w = resize_h, resize_w

    def transform(self, feature):
        img = feature.image
        squeeze = img.ndim == 2
        if squeeze:
            img = img[..., None]
        img = _resize_bilinear(img, self.h, self.w)
        feature.image = img[..., 0] if squeeze else img
        return feature


class AspectScale(FeatureTransformer):
    """Resize the short edge to `min_size`, keeping aspect ratio and capping
    the long edge (≙ augmentation/Resize.scala AspectScale)."""

    def __init__(self, min_size: int, max_size: int = 1000):
        self.min_size, self.max_size = min_size, max_size

    def transform(self, feature):
        h, w = feature.image.shape[:2]
        short, long = min(h, w), max(h, w)
        scale = min(self.min_size / short, self.max_size / long)
        feature.image = _resize_bilinear(
            feature.image, int(round(h * scale)), int(round(w * scale)))
        return feature


class RandomResize(FeatureTransformer):
    """Resize to a size drawn from [min_size, max_size]
    (≙ augmentation/RandomResize.scala)."""

    def __init__(self, min_size: int, max_size: int, seed: int = 0):
        self.min_size, self.max_size = min_size, max_size
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        s = int(self._rng.randint(self.min_size, self.max_size + 1))
        feature.image = _resize_bilinear(feature.image, s, s)
        return feature


class CenterCrop(FeatureTransformer):
    """≙ augmentation/Crop.scala CenterCrop."""

    def __init__(self, crop_width: int, crop_height: int):
        self.cw, self.ch = crop_width, crop_height

    def transform(self, feature):
        h, w = feature.image.shape[:2]
        y0, x0 = (h - self.ch) // 2, (w - self.cw) // 2
        feature.image = feature.image[y0:y0 + self.ch, x0:x0 + self.cw]
        return feature


class RandomCrop(FeatureTransformer):
    """≙ augmentation/Crop.scala RandomCrop."""

    def __init__(self, crop_width: int, crop_height: int, seed: int = 0):
        self.cw, self.ch = crop_width, crop_height
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        h, w = feature.image.shape[:2]
        y0 = int(self._rng.randint(0, h - self.ch + 1))
        x0 = int(self._rng.randint(0, w - self.cw + 1))
        feature.image = feature.image[y0:y0 + self.ch, x0:x0 + self.cw]
        return feature


class FixedCrop(FeatureTransformer):
    """Crop a fixed box; normalized coords if in [0,1]
    (≙ augmentation/Crop.scala FixedCrop)."""

    def __init__(self, x1: float, y1: float, x2: float, y2: float,
                 normalized: bool = False):
        self.box = (x1, y1, x2, y2)
        self.normalized = normalized

    def transform(self, feature):
        h, w = feature.image.shape[:2]
        x1, y1, x2, y2 = self.box
        if self.normalized:
            x1, x2 = x1 * w, x2 * w
            y1, y2 = y1 * h, y2 * h
        feature.image = feature.image[int(y1):int(y2), int(x1):int(x2)]
        return feature


class RandomCropper(FeatureTransformer):
    """Random crop + optional random flip, the ResNet ImageNet train recipe
    (≙ augmentation/RandomCropper.scala)."""

    def __init__(self, crop_width: int, crop_height: int, mirror: bool = True,
                 crop_mode: str = "random", channels: int = 3, seed: int = 0):
        self.cw, self.ch = crop_width, crop_height
        self.mirror = mirror
        self.crop_mode = crop_mode
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        h, w = feature.image.shape[:2]
        if self.crop_mode == "center":
            y0, x0 = (h - self.ch) // 2, (w - self.cw) // 2
        else:
            y0 = int(self._rng.randint(0, h - self.ch + 1))
            x0 = int(self._rng.randint(0, w - self.cw + 1))
        img = feature.image[y0:y0 + self.ch, x0:x0 + self.cw]
        if self.mirror and self._rng.uniform() < 0.5:
            img = img[:, ::-1]
        feature.image = np.ascontiguousarray(img)
        return feature


class RandomAlterAspect(FeatureTransformer):
    """Random scale+aspect-ratio crop resized to a fixed size, the Inception
    training crop (≙ augmentation/RandomAlterAspect.scala)."""

    def __init__(self, min_area_ratio: float = 0.08,
                 max_area_ratio: float = 1.0, min_aspect_ratio: float = 0.75,
                 target_size: int = 224, seed: int = 0):
        self.min_area = min_area_ratio
        self.max_area = max_area_ratio
        self.min_aspect = min_aspect_ratio
        self.target = target_size
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        h, w = feature.image.shape[:2]
        area = h * w
        for _ in range(10):
            t_area = area * self._rng.uniform(self.min_area, self.max_area)
            ar = self._rng.uniform(self.min_aspect, 1.0 / self.min_aspect)
            cw = int(round(np.sqrt(t_area * ar)))
            ch = int(round(np.sqrt(t_area / ar)))
            if cw <= w and ch <= h:
                y0 = int(self._rng.randint(0, h - ch + 1))
                x0 = int(self._rng.randint(0, w - cw + 1))
                crop = feature.image[y0:y0 + ch, x0:x0 + cw]
                feature.image = _resize_bilinear(crop, self.target,
                                                 self.target)
                return feature
        feature.image = _resize_bilinear(feature.image, self.target,
                                         self.target)
        return feature


class Expand(FeatureTransformer):
    """Place the image on a larger mean-filled canvas (SSD-style zoom-out;
    ≙ augmentation/Expand.scala)."""

    def __init__(self, means: Sequence[float] = (123.0, 117.0, 104.0),
                 max_expand_ratio: float = 4.0, seed: int = 0):
        self.means = np.asarray(means, np.float32)
        self.max_ratio = max_expand_ratio
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        ratio = self._rng.uniform(1.0, self.max_ratio)
        h, w, c = feature.image.shape
        nh, nw = int(h * ratio), int(w * ratio)
        y0 = int(self._rng.randint(0, nh - h + 1))
        x0 = int(self._rng.randint(0, nw - w + 1))
        canvas = np.tile(self.means[None, None, :], (nh, nw, 1))
        canvas[y0:y0 + h, x0:x0 + w] = feature.image
        feature.image = canvas.astype(np.float32)
        return feature


class Filler(FeatureTransformer):
    """Fill a (normalized-coord) sub-rectangle with a constant
    (≙ augmentation/Filler.scala)."""

    def __init__(self, start_x: float, start_y: float, end_x: float,
                 end_y: float, value: float = 255.0):
        self.box = (start_x, start_y, end_x, end_y)
        self.value = value

    def transform(self, feature):
        h, w = feature.image.shape[:2]
        x1, y1, x2, y2 = self.box
        feature.image[int(y1 * h):int(y2 * h), int(x1 * w):int(x2 * w)] = \
            self.value
        return feature


class HFlipVision(FeatureTransformer):
    """Unconditional horizontal flip (≙ augmentation/HFlip.scala; wrap in
    RandomTransformer for the probabilistic version)."""

    def transform(self, feature):
        feature.image = np.ascontiguousarray(feature.image[:, ::-1])
        return feature


class RandomTransformer(FeatureTransformer):
    """Apply `inner` with probability p (≙ augmentation/RandomTransformer.scala)."""

    def __init__(self, inner: FeatureTransformer, prob: float, seed: int = 0):
        self.inner = inner
        self.prob = prob
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        if self._rng.uniform() < self.prob:
            feature = self.inner.transform(feature)
        return feature


# --------------------------------------------------------------------- #
# photometric                                                           #
# --------------------------------------------------------------------- #
class Brightness(FeatureTransformer):
    """Add a uniform delta in [delta_low, delta_high]
    (≙ augmentation/Brightness.scala)."""

    def __init__(self, delta_low: float = -32.0, delta_high: float = 32.0,
                 seed: int = 0):
        self.low, self.high = delta_low, delta_high
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        feature.image = feature.image + \
            float(self._rng.uniform(self.low, self.high))
        return feature


class Contrast(FeatureTransformer):
    """Scale by a uniform factor (≙ augmentation/Contrast.scala)."""

    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5,
                 seed: int = 0):
        self.low, self.high = delta_low, delta_high
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        feature.image = feature.image * \
            float(self._rng.uniform(self.low, self.high))
        return feature


class Saturation(FeatureTransformer):
    """Blend with greyscale by a uniform factor
    (≙ augmentation/Saturation.scala)."""

    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5,
                 seed: int = 0):
        self.low, self.high = delta_low, delta_high
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        img = feature.image
        grey = (img[..., 0] * 0.299 + img[..., 1] * 0.587
                + img[..., 2] * 0.114)[..., None]
        alpha = float(self._rng.uniform(self.low, self.high))
        feature.image = img * alpha + grey * (1.0 - alpha)
        return feature


class Hue(FeatureTransformer):
    """Rotate hue by a uniform delta in degrees (≙ augmentation/Hue.scala;
    HSV roundtrip done in numpy instead of OpenCV)."""

    def __init__(self, delta_low: float = -18.0, delta_high: float = 18.0,
                 seed: int = 0):
        self.low, self.high = delta_low, delta_high
        self._rng = np.random.RandomState(seed)

    def transform(self, feature):
        img = np.clip(feature.image, 0, 255) / 255.0  # BGR
        b, g, r = img[..., 0], img[..., 1], img[..., 2]
        mx = img.max(-1)
        mn = img.min(-1)
        diff = mx - mn + 1e-12
        h = np.zeros_like(mx)
        rmax = mx == r
        gmax = (mx == g) & ~rmax
        bmax = ~(rmax | gmax)
        h[rmax] = (60 * (g - b) / diff)[rmax] % 360
        h[gmax] = (60 * (b - r) / diff + 120)[gmax]
        h[bmax] = (60 * (r - g) / diff + 240)[bmax]
        s = np.where(mx > 0, diff / (mx + 1e-12), 0.0)
        v = mx
        h = (h + float(self._rng.uniform(self.low, self.high))) % 360
        c = v * s
        hp = h / 60.0
        x = c * (1 - np.abs(hp % 2 - 1))
        z = np.zeros_like(c)
        conds = [hp < 1, hp < 2, hp < 3, hp < 4, hp < 5, hp >= 5]
        rgb = np.select(
            [cnd[..., None] for cnd in conds],
            [np.stack([c, x, z], -1), np.stack([x, c, z], -1),
             np.stack([z, c, x], -1), np.stack([z, x, c], -1),
             np.stack([x, z, c], -1), np.stack([c, z, x], -1)])
        rgb = rgb + (v - c)[..., None]
        feature.image = (rgb[..., ::-1] * 255.0).astype(np.float32)
        return feature


class ColorJitterVision(FeatureTransformer):
    """Random-order brightness/contrast/saturation(/hue)
    (≙ augmentation/ColorJitter.scala)."""

    def __init__(self, brightness_prob=0.5, brightness_delta=32.0,
                 contrast_prob=0.5, contrast_lower=0.5, contrast_upper=1.5,
                 saturation_prob=0.5, saturation_lower=0.5,
                 saturation_upper=1.5, hue_prob=0.5, hue_delta=18.0,
                 seed: int = 0):
        rng = np.random.RandomState(seed)
        self._rng = rng
        self.ops = [
            RandomTransformer(Brightness(-brightness_delta, brightness_delta,
                                         seed), brightness_prob, seed),
            RandomTransformer(Contrast(contrast_lower, contrast_upper, seed),
                              contrast_prob, seed),
            RandomTransformer(Saturation(saturation_lower, saturation_upper,
                                         seed), saturation_prob, seed),
            RandomTransformer(Hue(-hue_delta, hue_delta, seed), hue_prob,
                              seed),
        ]

    def transform(self, feature):
        order = np.arange(len(self.ops))
        self._rng.shuffle(order)
        for i in order:
            feature = self.ops[i].transform(feature)
        return feature


# --------------------------------------------------------------------- #
# normalize / layout                                                    #
# --------------------------------------------------------------------- #
class ChannelNormalize(FeatureTransformer):
    """(img - mean) / std per channel (≙ augmentation/ChannelNormalize.scala)."""

    def __init__(self, mean_b: float, mean_g: float, mean_r: float,
                 std_b: float = 1.0, std_g: float = 1.0, std_r: float = 1.0):
        self.mean = np.asarray([mean_b, mean_g, mean_r], np.float32)
        self.std = np.asarray([std_b, std_g, std_r], np.float32)

    def transform(self, feature):
        feature.image = (feature.image - self.mean) / self.std
        return feature


class ChannelScaledNormalizer(FeatureTransformer):
    """Per-channel mean subtraction + global scale
    (≙ augmentation/ChannelScaledNormalizer.scala)."""

    def __init__(self, mean_b: float, mean_g: float, mean_r: float,
                 scale: float):
        self.mean = np.asarray([mean_b, mean_g, mean_r], np.float32)
        self.scale = scale

    def transform(self, feature):
        feature.image = (feature.image - self.mean) * self.scale
        return feature


class PixelNormalizer(FeatureTransformer):
    """Subtract a whole mean image (≙ augmentation/PixelNormalizer.scala)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def transform(self, feature):
        feature.image = feature.image - self.means
        return feature


class ChannelOrder(FeatureTransformer):
    """Swap BGR <-> RGB (≙ augmentation/ChannelOrder.scala)."""

    def transform(self, feature):
        feature.image = np.ascontiguousarray(feature.image[..., ::-1])
        return feature


class MatToTensor(FeatureTransformer):
    """HWC -> CHW contiguous 'tensor' layout (≙ opencv MatToTensor.scala)."""

    def __init__(self, to_rgb: bool = False):
        self.to_rgb = to_rgb

    def transform(self, feature):
        img = feature.image
        if self.to_rgb:
            img = img[..., ::-1]
        feature.image = np.ascontiguousarray(np.transpose(img, (2, 0, 1)))
        return feature


class ImageFrameToSample(FeatureTransformer):
    """Attach Sample(chw, label) to each feature
    (≙ ImageFeatureToSample / convertor in Convertor.scala)."""

    def __init__(self, target_keys: Sequence[str] = ("label",)):
        self.target_keys = target_keys

    def transform(self, feature):
        img = feature.image
        chw = img if img.ndim == 3 and img.shape[0] in (1, 3) \
            else np.transpose(img, (2, 0, 1))
        label = feature.get(ImageFeature.LABEL)
        feature[ImageFeature.SAMPLE] = Sample(
            np.ascontiguousarray(chw, np.float32),
            None if label is None else np.float32(label))
        return feature
