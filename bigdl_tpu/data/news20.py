"""20-Newsgroups + GloVe loaders (≙ pyspark/bigdl/dataset/news20.py).

get_news20 reads the extracted `20news-18828` folder (class-per-subdir of
text files) from a local dir; with no data present returns a synthetic
corpus of class-templated sentences.  get_glove_w2v reads a local GloVe
txt; the fallback returns deterministic random vectors.
"""
from __future__ import annotations

import os
from typing import Dict, List, Tuple

import numpy as np

CLASS_NUM = 20


def _synthetic_news(n_per_class=8, classes=CLASS_NUM, seed=0):
    rng = np.random.RandomState(seed)
    topics = [f"topic{c} subject{c} theme{c} matter{c}"
              for c in range(classes)]
    filler = ["the quick brown fox", "jumps over", "a lazy dog",
              "hello world example", "sample sentence text"]
    out = []
    for c in range(classes):
        for _ in range(n_per_class):
            words = [topics[c]] + [filler[rng.randint(len(filler))]
                                   for _ in range(rng.randint(3, 8))]
            rng.shuffle(words)
            out.append((" ".join(words), c + 1))  # 1-based labels
    return out


def get_news20(source_dir="./data/news20/") -> List[Tuple[str, int]]:
    """Returns [(text, 1-based label)] (≙ news20.py get_news20)."""
    news_dir = os.path.join(source_dir, "20news-18828")
    if not os.path.isdir(news_dir):
        return _synthetic_news()
    texts = []
    classes = sorted(os.listdir(news_dir))
    for label_id, cname in enumerate(classes, start=1):
        cdir = os.path.join(news_dir, cname)
        if not os.path.isdir(cdir):
            continue
        for fname in sorted(os.listdir(cdir)):
            fpath = os.path.join(cdir, fname)
            try:
                with open(fpath, encoding="latin-1") as f:
                    content = f.read()
                texts.append((content, label_id))
            except OSError:
                continue
    return texts


def get_glove_w2v(source_dir="./data/news20/", dim=100) -> Dict[str, np.ndarray]:
    """Returns {word: vector} (≙ news20.py get_glove_w2v)."""
    glove_path = os.path.join(source_dir, "glove.6B",
                              f"glove.6B.{dim}d.txt")
    if not os.path.exists(glove_path):
        rng = np.random.RandomState(0)
        vocab = ([f"topic{c}" for c in range(CLASS_NUM)]
                 + "the quick brown fox jumps over a lazy dog hello world "
                   "example sample sentence text subject theme matter".split())
        return {w: rng.randn(dim).astype(np.float32) for w in vocab}
    w2v = {}
    with open(glove_path, encoding="latin-1") as f:
        for line in f:
            parts = line.rstrip().split(" ")
            w2v[parts[0]] = np.asarray(parts[1:], np.float32)
    return w2v
