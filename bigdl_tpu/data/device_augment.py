"""Device-side (jit-able) image augmentation.

The reference augments on the CPU with OpenCV inside Spark tasks
(transform/vision/image/augmentation/*.scala); on TPU the same random
crop / flip / normalize can run ON DEVICE inside the train step — the
host ships raw uint8 batches (4x smaller than fp32 over PCIe) and the
augmentation fuses into the step's XLA program, so the input pipeline
costs no host wall-clock at all.

All functions are pure (params, rng, batch) -> batch and shape-static:
random crops use ``lax.dynamic_slice`` with traced offsets, so one
compiled program serves every step.

    aug = DeviceAugment(crop=(224, 224), flip=True,
                        mean=(0.485, 0.456, 0.406) * 255,
                        std=(0.229, 0.224, 0.225) * 255)
    x = aug(raw_uint8_nhwc, rng)           # inside jit / the train step
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def random_crop(x, rng, crop_h, crop_w):
    """Per-image random crop of an NHWC batch (traced offsets)."""
    n, h, w, c = x.shape
    ky, kx = jax.random.split(rng)
    oy = jax.random.randint(ky, (n,), 0, h - crop_h + 1)
    ox = jax.random.randint(kx, (n,), 0, w - crop_w + 1)

    def one(img, y0, x0):
        return lax.dynamic_slice(img, (y0, x0, 0), (crop_h, crop_w, c))

    return jax.vmap(one)(x, oy, ox)


def center_crop(x, crop_h, crop_w):
    n, h, w, c = x.shape
    y0, x0 = (h - crop_h) // 2, (w - crop_w) // 2
    return x[:, y0:y0 + crop_h, x0:x0 + crop_w]


def random_hflip(x, rng, p=0.5):
    """Per-image horizontal flip of an NHWC batch."""
    flip = jax.random.bernoulli(rng, p, (x.shape[0],))
    return jnp.where(flip[:, None, None, None], x[:, :, ::-1], x)


def normalize(x, mean, std, dtype=jnp.float32):
    mean = jnp.asarray(mean, jnp.float32)
    std = jnp.asarray(std, jnp.float32)
    return ((x.astype(jnp.float32) - mean) / std).astype(dtype)


def to_nchw(x):
    return jnp.transpose(x, (0, 3, 1, 2))


class DeviceAugment:
    """Composable on-device train/eval augmentation for uint8 NHWC batches.

    crop: (h, w) random crop at train time, center crop at eval;
    flip: random horizontal flip (train only); mean/std: per-channel
    normalization (in 0..255 units for uint8 inputs); out_format:
    'NCHW' (reference layout) or 'NHWC'; dtype: compute dtype of the
    returned batch (e.g. jnp.bfloat16 to feed the MXU directly).
    """

    def __init__(self, crop=None, flip=False, mean=(0.0, 0.0, 0.0),
                 std=(1.0, 1.0, 1.0), out_format="NCHW",
                 dtype=jnp.float32):
        self.crop = crop
        self.flip = flip
        self.mean = tuple(mean)
        self.std = tuple(std)
        self.out_format = out_format
        self.dtype = dtype

    def __call__(self, x, rng=None, training=True):
        if training and rng is None and (self.crop or self.flip):
            raise ValueError("training-mode augmentation needs rng=")
        if self.crop is not None:
            ch, cw = self.crop
            if training:
                rng, sub = jax.random.split(rng)
                x = random_crop(x, sub, ch, cw)
            else:
                x = center_crop(x, ch, cw)
        if self.flip and training:
            rng, sub = jax.random.split(rng)
            x = random_hflip(x, sub)
        x = normalize(x, self.mean, self.std, self.dtype)
        if self.out_format == "NCHW":
            x = to_nchw(x)
        return x
