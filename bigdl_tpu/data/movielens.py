"""MovieLens-1M loader (≙ pyspark/bigdl/dataset/movielens.py).

Reads ml-1m/ratings.dat ("uid::mid::rating::timestamp") from a local dir;
synthesizes a deterministic rating matrix sample when absent (zero egress).

Recommendation-stream extensions (the sharded-embedding workload):

  * :func:`leave_one_out` — deterministic per-user train/eval split
    (each user's latest rating held out; ties broken by movie id);
  * :func:`rating_samples` / :func:`write_rating_shards` — turn ratings
    into ``(uid_list, mid_list, label)`` samples with a RAGGED movie-id
    list (target + recent history) and pack them into TFRecord shards
    the PR-9 :class:`~bigdl_tpu.data.sharded.ShardedRecordDataSet`
    streams with its exactly-once cursor protocol — ragged payloads are
    invisible to the cursor, which tracks byte records;
  * :func:`decode_sample` / :func:`padded_collate` — the pipeline hooks:
    decode yields ragged numpy id lists, the collate pads them to the
    static bucket ladder of :mod:`bigdl_tpu.embedding.dedup` so warm
    streams present a finite shape set (recompile-free after warmup).
"""
from __future__ import annotations

import os
import struct

import numpy as np


def _synthetic(n_users=200, n_movies=120, n_ratings=4000, seed=0):
    rng = np.random.RandomState(seed)
    uid = rng.randint(1, n_users + 1, n_ratings)
    mid = rng.randint(1, n_movies + 1, n_ratings)
    # structured ratings: users like movies whose id mod 5 matches theirs
    base = 3.0 + ((uid % 5) == (mid % 5)) * 1.5 - ((uid % 7) == 0) * 1.0
    rating = np.clip(np.round(base + rng.randn(n_ratings) * 0.5), 1, 5)
    ts = rng.randint(9e8, 1e9, n_ratings)
    return np.stack([uid, mid, rating.astype(np.int64), ts], 1)


def read_data_sets(data_dir):
    """Returns int array [N, 4] of (userid, movieid, rating, timestamp)."""
    rating_file = os.path.join(data_dir, "ml-1m", "ratings.dat")
    if not os.path.exists(rating_file):
        return _synthetic()
    rows = []
    with open(rating_file) as f:
        for line in f:
            rows.append([int(float(v)) for v in line.strip().split("::")])
    return np.asarray(rows, np.int64)


def get_id_pairs(data_dir):
    return read_data_sets(data_dir)[:, 0:2]


def get_id_ratings(data_dir):
    return read_data_sets(data_dir)[:, 0:3]


# --------------------------------------------------------------------- #
# recommendation stream: leave-one-out split + ragged-ID samples        #
# --------------------------------------------------------------------- #
def leave_one_out(ratings):
    """Deterministic per-user split: each user's LAST rating (max
    timestamp, ties broken by movie id, then position) goes to eval,
    the rest to train.  Returns (train, eval) int64 [*, 4] arrays in
    the original row order."""
    ratings = np.asarray(ratings, np.int64)
    order = np.lexsort((np.arange(len(ratings)), ratings[:, 1],
                        ratings[:, 3], ratings[:, 0]))
    held = {}
    for i in order:          # ascending: the last seen per user wins
        held[int(ratings[i, 0])] = int(i)
    eval_mask = np.zeros(len(ratings), bool)
    eval_mask[list(held.values())] = True
    return ratings[~eval_mask], ratings[eval_mask]


def rating_samples(ratings, max_hist: int = 8, threshold: int = 4):
    """``(uid_list, mid_list, label)`` samples from a rating table.

    Per rating, in (user, timestamp) order: ``uid_list = [uid]``,
    ``mid_list = [target_mid] + up to max_hist previous mids`` (newest
    first — RAGGED, length 1..1+max_hist), ``label = 1.0`` iff rating >=
    ``threshold``.  Sample order matches the input row order, so the
    stream is deterministic."""
    ratings = np.asarray(ratings, np.int64)
    order = np.lexsort((np.arange(len(ratings)), ratings[:, 3],
                        ratings[:, 0]))
    hist = {}
    by_row = [None] * len(ratings)
    for i in order:
        uid, mid, rating = (int(ratings[i, 0]), int(ratings[i, 1]),
                            int(ratings[i, 2]))
        prev = hist.setdefault(uid, [])
        mids = [mid] + prev[:max_hist]
        by_row[i] = ([uid], mids, 1.0 if rating >= threshold else 0.0)
        prev.insert(0, mid)
    return by_row


def encode_sample(uid_list, mid_list, label) -> bytes:
    """Variable-length record: ``<f label | <i nu | nu ids | <i nm |
    nm ids`` — the ragged-ID payload shape of the cursor protocol."""
    u = [int(x) for x in uid_list]
    m = [int(x) for x in mid_list]
    return struct.pack(f"<fi{len(u)}ii{len(m)}i", float(label),
                       len(u), *u, len(m), *m)


def decode_sample(b: bytes):
    """Inverse of :func:`encode_sample`: ``((uid_arr, mid_arr), label)``
    with ragged int32 id arrays — collate pads them (a decode hook for
    ShardedRecordDataSet)."""
    label, nu = struct.unpack_from("<fi", b, 0)
    off = 8
    uids = np.frombuffer(b, "<i4", nu, off)
    (nm,) = struct.unpack_from("<i", b, off + 4 * nu)
    mids = np.frombuffer(b, "<i4", nm, off + 4 * nu + 4)
    return ((uids.astype(np.int32), mids.astype(np.int32)),
            np.float32(label))


def write_rating_shards(out_dir, ratings=None, n_files: int = 4,
                        max_hist: int = 8, threshold: int = 4):
    """Pack ratings (default: the synthetic table) into ``n_files``
    TFRecord shards of ragged-ID samples; returns the shard paths.
    Samples are dealt round-robin so every shard sees every user mix."""
    from ..utils.tfrecord import write_tfrecords
    if ratings is None:
        ratings = _synthetic()
    samples = rating_samples(ratings, max_hist=max_hist,
                             threshold=threshold)
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for f in range(n_files):
        recs = [encode_sample(*s) for s in samples[f::n_files]]
        p = os.path.join(out_dir, f"ratings-{f:04d}.tfr")
        write_tfrecords(p, recs)
        paths.append(p)
    return paths


def padded_collate(ladder=None, min_uid_len: int = 1,
                   min_mid_len: int = 16):
    """Collate hook for the sharded pipeline: pad ragged
    ``(uid_arr, mid_arr)`` samples to the static bucket ladder and
    emit ``((uids (B, Lu), mids (B, Lm)), labels (B, 1))`` — copying,
    so the staged batch owns its memory (the pipeline's owned-buffer
    rule).  Pinning ``min_mid_len`` above the max ragged length makes
    the warm stream single-shape (zero recompiles)."""
    from ..embedding.dedup import DEFAULT_LADDER, pad_ragged
    ladder = tuple(ladder or (1, 2, 4) + tuple(DEFAULT_LADDER))

    def collate(samples):
        xs, ys = zip(*samples)
        uids = pad_ragged([u for u, _ in xs], ladder, min_len=min_uid_len)
        mids = pad_ragged([m for _, m in xs], ladder, min_len=min_mid_len)
        labels = np.asarray(ys, np.float32).reshape(-1, 1)
        return (uids, mids), labels

    return collate


def sharded_rating_dataset(paths, batch_size: int = 32, n_workers: int = 2,
                           seed: int = 7, min_mid_len: int = 16, **kw):
    """ShardedRecordDataSet over rating shards with the ragged decode +
    padded collate wired in — exactly-once and cursor-resume semantics
    come from the PR-9 pipeline unchanged."""
    from .sharded import ShardedRecordDataSet
    return ShardedRecordDataSet(
        paths, "tfrecord", lambda b: decode_sample(b),
        batch_size=batch_size, n_workers=n_workers, seed=seed,
        collate=padded_collate(min_mid_len=min_mid_len), **kw)
