"""MovieLens-1M loader (≙ pyspark/bigdl/dataset/movielens.py).

Reads ml-1m/ratings.dat ("uid::mid::rating::timestamp") from a local dir;
synthesizes a deterministic rating matrix sample when absent (zero egress).
"""
from __future__ import annotations

import os

import numpy as np


def _synthetic(n_users=200, n_movies=120, n_ratings=4000, seed=0):
    rng = np.random.RandomState(seed)
    uid = rng.randint(1, n_users + 1, n_ratings)
    mid = rng.randint(1, n_movies + 1, n_ratings)
    # structured ratings: users like movies whose id mod 5 matches theirs
    base = 3.0 + ((uid % 5) == (mid % 5)) * 1.5 - ((uid % 7) == 0) * 1.0
    rating = np.clip(np.round(base + rng.randn(n_ratings) * 0.5), 1, 5)
    ts = rng.randint(9e8, 1e9, n_ratings)
    return np.stack([uid, mid, rating.astype(np.int64), ts], 1)


def read_data_sets(data_dir):
    """Returns int array [N, 4] of (userid, movieid, rating, timestamp)."""
    rating_file = os.path.join(data_dir, "ml-1m", "ratings.dat")
    if not os.path.exists(rating_file):
        return _synthetic()
    rows = []
    with open(rating_file) as f:
        for line in f:
            rows.append([int(float(v)) for v in line.strip().split("::")])
    return np.asarray(rows, np.int64)


def get_id_pairs(data_dir):
    return read_data_sets(data_dir)[:, 0:2]


def get_id_ratings(data_dir):
    return read_data_sets(data_dir)[:, 0:3]
