"""Sample / MiniBatch (≙ dataset/Sample.scala, MiniBatch.scala).

A Sample holds (features, labels) as numpy arrays (host side).  A MiniBatch
is the batched device-feedable pair, with optional padding to fixed shapes —
fixed shapes matter on TPU: every distinct shape triggers an XLA recompile,
so SampleToMiniBatch always pads to a static max shape when sizes vary.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


class Sample:
    def __init__(self, feature, label=None):
        self.features = feature if isinstance(feature, (list, tuple)) \
            else [feature]
        self.features = [np.asarray(f) for f in self.features]
        if label is None:
            self.labels = []
        else:
            labels = label if isinstance(label, (list, tuple)) else [label]
            self.labels = [np.asarray(l) for l in labels]

    def feature(self, i=0):
        return self.features[i]

    def label(self, i=0):
        return self.labels[i] if self.labels else None

    def __repr__(self):
        return (f"Sample(features={[f.shape for f in self.features]}, "
                f"labels={[l.shape for l in self.labels]})")


class PaddingParam:
    """Fixed-length padding spec (≙ dataset/MiniBatch.scala PaddingParam)."""

    def __init__(self, padding_value=0.0, fixed_length=None):
        self.padding_value = padding_value
        self.fixed_length = fixed_length


class MiniBatch:
    def __init__(self, input, target=None):
        self.input = input
        self.target = target

    def get_input(self):
        return self.input

    def get_target(self):
        return self.target

    def size(self):
        first = self.input[0] if isinstance(self.input, (list, tuple)) \
            else self.input
        return first.shape[0]

    def slice(self, offset, length):
        """1-based offset slice, matching reference MiniBatch.slice."""
        def sl(x):
            if isinstance(x, (list, tuple)):
                return [sl(e) for e in x]
            return x[offset - 1: offset - 1 + length]
        return MiniBatch(sl(self.input),
                         None if self.target is None else sl(self.target))


def _pad_stack(arrays: Sequence[np.ndarray], padding: Optional[PaddingParam]):
    shapes = {a.shape for a in arrays}
    if len(shapes) == 1 and (padding is None or padding.fixed_length is None):
        return np.stack(arrays)
    ndim = arrays[0].ndim
    max_shape = [max(a.shape[d] for a in arrays) for d in range(ndim)]
    if padding is not None and padding.fixed_length is not None:
        fl = padding.fixed_length
        if isinstance(fl, int):
            max_shape[0] = max(max_shape[0], fl)
        else:
            for d, v in enumerate(fl):
                if v is not None and v > 0:
                    max_shape[d] = max(max_shape[d], v)
    value = 0.0 if padding is None else padding.padding_value
    out = np.full([len(arrays)] + max_shape, value, dtype=arrays[0].dtype)
    for i, a in enumerate(arrays):
        out[(i,) + tuple(slice(0, s) for s in a.shape)] = a
    return out


def samples_to_minibatch(samples: List[Sample],
                         feature_padding: Optional[PaddingParam] = None,
                         label_padding: Optional[PaddingParam] = None) -> MiniBatch:
    n_feat = len(samples[0].features)
    feats = [_pad_stack([s.features[i] for s in samples], feature_padding)
             for i in range(n_feat)]
    n_lab = len(samples[0].labels)
    labs = [_pad_stack([s.labels[i] for s in samples], label_padding)
            for i in range(n_lab)]
    input_ = feats[0] if n_feat == 1 else feats
    target = None if n_lab == 0 else (labs[0] if n_lab == 1 else labs)
    return MiniBatch(input_, target)
