"""Elastic supervisor: shrink on preemption, regrow on capacity.

The PR-3 PreemptionHandler turns SIGTERM into "commit a final
checkpoint and stop cleanly"; the PR-4 health layer turns a sick run
into signals.  This module closes the loop: a retry/backoff state
machine that, instead of letting a preempted or degraded job die,

  1. **drains** — finishes the in-flight async write and commits a
     final elastic (v2, mesh-recorded) checkpoint;
  2. **re-plans** — asks :func:`.plan.plan_mesh` for the largest mesh
     the *surviving* capacity supports (shrinking ``dp`` first);
  3. **resumes** — rebuilds the trainer on the new mesh and restores
     through the reshard path (global arrays are mesh-invariant, so a
     shrink is a re-layout, not a loss of progress);
  4. **regrows** — keeps polling capacity and, at a checkpoint
     boundary, scales back up the same way when devices return.

Capacity is an injected ``capacity_fn`` (default: ``jax.devices()``) —
the seam where a cluster scheduler, the stall watchdog's straggler
verdict, or a test harness reports which devices are usable.  Data is
an injected ``batch_fn(step)`` so a rebuilt segment regenerates its
batches deterministically (the stateless analogue of the PR-3 data
cursor; fixed GLOBAL batch across replans keeps the math identical).

Every transition lands in the Recorder as ``elastic/*`` counters and
``elastic_event`` + ``health_event`` records, so /metrics and
``trace_summary health`` show the shrink/regrow history.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

from .plan import _prod, plan_devices, plan_mesh


class ElasticSupervisor:
    """Drive an :class:`~bigdl_tpu.parallel.spmd.SpmdTrainer` factory
    through preemptions and capacity changes.

    ``trainer_factory(mesh)`` must return a fresh, un-``init()``-ed
    trainer for that mesh (the supervisor owns checkpoint wiring).
    """

    def __init__(self, trainer_factory, ckpt_dir: str,
                 template: Dict[str, int], *,
                 capacity_fn: Optional[Callable] = None,
                 batch_fn: Optional[Callable] = None,
                 recorder=None, ckpt_every: int = 50, keep: int = 3,
                 shard_arrays: bool = True,
                 min_axes: Optional[Dict[str, int]] = None,
                 replan_every: int = 10, max_restarts: int = 5,
                 backoff_base: float = 0.5, backoff_max: float = 30.0,
                 handle_sigterm: bool = True):
        self.trainer_factory = trainer_factory
        self.ckpt_dir = str(ckpt_dir)
        self.template = {str(k): int(v) for k, v in template.items()}
        self.capacity_fn = capacity_fn
        self.batch_fn = batch_fn
        self._recorder = recorder
        self.ckpt_every = int(ckpt_every)
        self.keep = int(keep)
        self.shard_arrays = bool(shard_arrays)
        self.min_axes = dict(min_axes or {})
        self.replan_every = int(replan_every)
        self.max_restarts = int(max_restarts)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.handle_sigterm = bool(handle_sigterm)
        self.state = "idle"
        self.restarts = 0
        self.trainer = None
        self._stop = False
        self._preemption = None

    # ------------------------------------------------------------------ #
    def _rec(self):
        if self._recorder is not None:
            return self._recorder
        from ..observability import null_recorder
        return null_recorder()

    def _capacity(self) -> list:
        import jax
        cap = self.capacity_fn() if self.capacity_fn is not None \
            else jax.devices()
        if isinstance(cap, int):
            cap = jax.devices()[:cap]
        return list(cap)

    def _event(self, kind: str, **fields):
        rec = self._rec()
        rec.inc(f"elastic/{kind}s" if not kind.endswith("s")
                else f"elastic/{kind}")
        rec.inc(f"health/elastic_{kind}")
        rec.emit_record("elastic_event", kind=kind, state=self.state,
                        **fields)
        rec.emit_record("health_event", condition=f"elastic_{kind}",
                        step=fields.get("step"), metric="elastic/devices",
                        value=fields.get("devices"), threshold=None,
                        action="elastic")

    def _set_state(self, state: str):
        self.state = state
        self._rec().gauge("elastic/state_" + state, time.time())

    def stop(self):
        """Ask run() to commit a checkpoint and return at the next
        step boundary (callable from any thread)."""
        self._stop = True

    # ------------------------------------------------------------------ #
    def _build(self, axes, devices):
        from ..parallel import mesh as mesh_lib
        mesh = mesh_lib.create_mesh(dict(axes),
                                    plan_devices(axes, devices))
        trainer = self.trainer_factory(mesh)
        if self._recorder is not None and trainer._recorder is None:
            # one recorder across every segment: the trainer's
            # elastic/reshard + checkpoint counters land in the same
            # ring the supervisor's events do (set BEFORE init() — the
            # health variant changes the compiled step)
            trainer.set_telemetry(self._recorder)
        trainer.set_checkpoint(self.ckpt_dir, every_steps=self.ckpt_every,
                               keep=self.keep, layout="manifest",
                               shard_arrays=self.shard_arrays)
        trainer.init()
        try:
            trainer.load_checkpoint(self.ckpt_dir)
            resumed = True
        except FileNotFoundError:
            resumed = False     # fresh run: nothing to restore yet
        return trainer, resumed

    def _teardown(self, trainer):
        try:
            if trainer._ckpt_mgr is not None:
                trainer._ckpt_mgr.wait()
        finally:
            trainer.detach()

    def run(self, batch_fn: Optional[Callable] = None,
            steps: int = 100) -> list:
        """Train to ``steps`` total steps across however many meshes it
        takes; returns the per-step losses (recomputed steps — the tail
        a failure rolled back — keep their latest value)."""
        batch_fn = batch_fn or self.batch_fn
        if batch_fn is None:
            raise ValueError("no batch_fn: pass one here or at init")
        self._stop = False      # re-arm: a stop()ped supervisor can run again
        rec = self._rec()
        if self.handle_sigterm:
            from ..checkpoint import PreemptionHandler
            if self._preemption is None:
                self._preemption = PreemptionHandler()
            self._preemption.install()
        handler = self._preemption
        losses: Dict[int, Any] = {}     # device scalars until segment drain
        prev_axes = None
        first_step = None
        try:
            while True:
                self._set_state("planning")
                devices = self._capacity()
                axes = plan_mesh(len(devices), self.template,
                                 self.min_axes)
                rec.gauge("elastic/devices", _prod(axes))
                for name, size in axes.items():
                    rec.gauge(f"elastic/axis_{name}", size)
                self._set_state("resuming")
                try:
                    trainer, resumed = self._build(axes, devices)
                except Exception:
                    if not self._backoff("build"):
                        raise
                    continue
                if prev_axes is not None and axes != prev_axes:
                    # emitted only AFTER a successful build: a failed
                    # build's plan is a mesh the job never ran on, and
                    # must not show up as a topology transition
                    kind = "shrink" if _prod(axes) < _prod(prev_axes) \
                        else "regrow"
                    self._event(kind, from_axes=prev_axes, to_axes=axes,
                                devices=_prod(axes))
                    print(f"[elastic] {kind}: {prev_axes} -> {axes}",
                          flush=True)
                prev_axes = axes
                self.trainer = trainer
                if resumed:
                    self._event("resume", step=trainer._step_count,
                                devices=_prod(axes), axes=axes)
                start = trainer._step_count
                if first_step is None:
                    first_step = start
                outcome, fail = "completed", None
                self._set_state("running")
                try:
                    for s in range(start, steps):
                        if self._stop:
                            outcome = "stopped"
                            break
                        if handler is not None and handler.requested:
                            outcome = "preempted"
                            break
                        if (self.replan_every and s > start
                                and (s - start) % self.replan_every == 0):
                            new_axes = plan_mesh(len(self._capacity()),
                                                 self.template,
                                                 self.min_axes)
                            if new_axes != axes:
                                outcome = "replan"
                                break
                        tokens, targets = batch_fn(s)
                        # device scalar, no float(): a per-step host
                        # sync would serialize dispatch against
                        # execution (GL002) — the floats are only
                        # needed at segment boundaries, and the bulk
                        # sync below runs before the mesh is torn down
                        losses[s] = trainer.step(tokens, targets)
                        rec.gauge("elastic/steps_done", s + 1)
                        if (self.ckpt_every
                                and (s + 1) % self.ckpt_every == 0
                                and s + 1 < steps):
                            trainer.save_checkpoint(self.ckpt_dir)
                    # one bulk device→host sync per SEGMENT (GL002):
                    # the scalars must materialize before this mesh is
                    # torn down — and inside the try, so a device lost
                    # mid-drain is retried/replanned like any other
                    # segment failure, not a supervisor death
                    self._drain_losses(losses, strict=True)
                except Exception as e:      # noqa: BLE001 — retried
                    outcome, fail = "failed", e
                    # best effort on the failure path: keep what still
                    # materializes, drop dead-mesh scalars (the resume
                    # recomputes everything past the last checkpoint)
                    self._drain_losses(losses, strict=False)
                self._set_state("draining")
                if outcome == "failed":
                    self._teardown(self.trainer)
                    self.trainer = None
                    if not self._backoff("segment", fail):
                        raise fail
                    continue
                # clean outcomes commit a final synchronous checkpoint:
                # nothing after this point can lose a completed step.
                # A zero-new-step resumed segment skips it — its state
                # is bit-identical to the checkpoint just restored, and
                # rewriting every shard would stall shutdown for a full
                # write for zero progress
                tag = f"preempt_step_{trainer._step_count}" \
                    if outcome == "preempted" else None
                if trainer._step_count > start or not resumed:
                    trainer.save_checkpoint(self.ckpt_dir, sync=True,
                                            tag=tag)
                self._teardown(trainer)
                self.trainer = None
                self.restarts = 0           # a committed segment resets
                if outcome == "preempted":
                    self._event("preemption", step=trainer._step_count,
                                devices=_prod(axes))
                    print(f"[elastic] preempted at step "
                          f"{trainer._step_count}; final checkpoint "
                          "committed, re-planning from surviving "
                          "capacity", flush=True)
                    handler.reset()
                    continue
                if outcome == "replan":
                    continue
                self._set_state("idle")
                # `in losses`: a failed segment may have dropped dead-
                # mesh scalars that no later resume recomputed (steps
                # before its own mid-segment checkpoint)
                return [losses[s]
                        for s in range(first_step, max(losses) + 1)
                        if s in losses] \
                    if losses else []
        finally:
            if self.handle_sigterm and handler is not None:
                handler.uninstall()

    @staticmethod
    def _drain_losses(losses: Dict[int, Any], strict: bool):
        """Materialize the segment's device scalars to floats in place.
        ``strict=False`` (the segment-failure path) drops entries whose
        buffers died with the mesh instead of raising — those steps are
        recomputed past the restored checkpoint anyway."""
        for k, v in list(losses.items()):
            if isinstance(v, float):
                continue
            try:
                losses[k] = float(v)
            except Exception:
                if strict:
                    raise
                losses.pop(k)
        return losses

    def _backoff(self, what: str, exc: Exception = None) -> bool:
        """Count a failure; sleep exponentially; False when retries are
        exhausted (caller re-raises)."""
        self.restarts += 1
        self._event("failure", attempt=self.restarts, what=what,
                    error=None if exc is None else repr(exc))
        if self.restarts > self.max_restarts:
            return False
        delay = min(self.backoff_base * (2 ** (self.restarts - 1)),
                    self.backoff_max)
        print(f"[elastic] {what} failed ({exc!r}); retry "
              f"{self.restarts}/{self.max_restarts} in {delay:.1f}s",
              flush=True)
        time.sleep(delay)
        return True
