"""Elastic supervisor: shrink on preemption, regrow on capacity.

The PR-3 PreemptionHandler turns SIGTERM into "commit a final
checkpoint and stop cleanly"; the PR-4 health layer turns a sick run
into signals.  This module closes the loop: a retry/backoff state
machine that, instead of letting a preempted or degraded job die,

  1. **drains** — finishes the in-flight async write and commits a
     final elastic (v2, mesh-recorded) checkpoint;
  2. **re-plans** — asks :func:`.plan.plan_mesh` for the largest mesh
     the *surviving* capacity supports (shrinking ``dp`` first);
  3. **resumes** — rebuilds the trainer on the new mesh and restores
     through the reshard path (global arrays are mesh-invariant, so a
     shrink is a re-layout, not a loss of progress);
  4. **regrows** — keeps polling capacity and, at a checkpoint
     boundary, scales back up the same way when devices return.

Capacity is an injected ``capacity_fn`` (default: ``jax.devices()``) —
the seam where a cluster scheduler, the stall watchdog's straggler
verdict, or a test harness reports which devices are usable.  Data is
an injected ``batch_fn(step)`` so a rebuilt segment regenerates its
batches deterministically (the stateless analogue of the PR-3 data
cursor; fixed GLOBAL batch across replans keeps the math identical).

Every transition lands in the Recorder as ``elastic/*`` counters and
``elastic_event`` + ``health_event`` records, so /metrics and
``trace_summary health`` show the shrink/regrow history.

**Hang-abort** (``hang_abort_grace=``): a step that never finishes is
the failure mode retries can't see — the loop is blocked INSIDE
``trainer.step``.  The supervisor arms the PR-4 :class:`StallWatchdog`
with an escalation policy: grace seconds past stall detection, the
watchdog dumps a flight record and the supervisor raises
:class:`HangAbortError` *asynchronously in the step-loop thread*
(``PyThreadState_SetAsyncExc`` — lands at the next bytecode boundary,
so it aborts Python-level wedges: a stuck retry loop, a poisoned
queue wait, an injected ``step.dispatch`` delay; a hang inside a
native/XLA call is only interruptible at process level, which the
flight dump serves).  The segment's existing failure path catches it:
teardown, backoff, re-plan, resume from the last checkpoint — a wedged
step becomes a replan instead of an operator page.

Backoff runs through :class:`~bigdl_tpu.utils.retry.RetryPolicy`
(``jitter=False`` reproduces the exact legacy
``min(base * 2**(n-1), max)`` schedule — equivalence-tested), so
supervisor restarts share the ``retry/*`` counters with every other
retry loop in the repo.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from .plan import _prod, plan_devices, plan_mesh
from .. import faults as faultplane
from ..observability import tracing as trace_spine
from ..observability.context import TraceContext
from ..utils.retry import RetryPolicy


class HangAbortError(RuntimeError):
    """Raised asynchronously in the supervisor's step loop when the
    watchdog's hang-abort escalation fires; handled as a segment
    failure (replan-and-resume), never propagated to the caller unless
    restarts are exhausted."""


def _async_raise(thread_ident: int, exc_type) -> bool:
    """Raise ``exc_type`` in the thread with ``thread_ident`` at its
    next bytecode boundary.  Returns False when the thread is gone (or
    the interpreter refused) — the caller logs rather than assumes."""
    import ctypes
    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(thread_ident), ctypes.py_object(exc_type))
    if res > 1:         # >1 = multiple states touched: undo, refuse
        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(thread_ident), None)
        return False
    return res == 1


class ElasticSupervisor:
    """Drive an :class:`~bigdl_tpu.parallel.spmd.SpmdTrainer` factory
    through preemptions and capacity changes.

    ``trainer_factory(mesh)`` must return a fresh, un-``init()``-ed
    trainer for that mesh (the supervisor owns checkpoint wiring).
    """

    def __init__(self, trainer_factory, ckpt_dir: str,
                 template: Dict[str, int], *,
                 capacity_fn: Optional[Callable] = None,
                 batch_fn: Optional[Callable] = None,
                 recorder=None, ckpt_every: int = 50, keep: int = 3,
                 shard_arrays: bool = True,
                 min_axes: Optional[Dict[str, int]] = None,
                 axis_costs: Optional[Dict[str, float]] = None,
                 replan_every: int = 10, max_restarts: int = 5,
                 backoff_base: float = 0.5, backoff_max: float = 30.0,
                 handle_sigterm: bool = True,
                 hang_abort_grace: Optional[float] = None,
                 watchdog=None, flight_dir: Optional[str] = None,
                 name: Optional[str] = None):
        self.trainer_factory = trainer_factory
        self.ckpt_dir = str(ckpt_dir)
        self.template = {str(k): int(v) for k, v in template.items()}
        self.capacity_fn = capacity_fn
        self.batch_fn = batch_fn
        self._recorder = recorder
        self.ckpt_every = int(ckpt_every)
        self.keep = int(keep)
        self.shard_arrays = bool(shard_arrays)
        self.min_axes = dict(min_axes or {})
        # per-axis shrink costs for 4-axis templates: replans shrink the
        # cheapest viable axis (plan.AXIS_SHRINK_COST defaults; override
        # when a job's tp/pp re-layout economics differ)
        self.axis_costs = None if axis_costs is None else dict(axis_costs)
        self.replan_every = int(replan_every)
        self.max_restarts = int(max_restarts)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.handle_sigterm = bool(handle_sigterm)
        # the fleet job name: labels this supervisor's events and retry
        # counters so N jobs sharing one recorder stay attributable
        self.name = None if name is None else str(name)
        # the unified backoff: jitter=False reproduces the legacy
        # min(base * 2**(n-1), max) schedule bit-for-bit, and the
        # retry/* counters make restarts observable next to every
        # other retry loop in the repo.  A named (fleet) supervisor
        # splits them per job — retry/attempts.elastic.<job> — because
        # N jobs sharing a recorder would otherwise collide on one
        # retry/attempts.elastic counter
        self.retry = RetryPolicy(max_attempts=self.max_restarts + 1,
                                 base=self.backoff_base,
                                 max_delay=self.backoff_max,
                                 jitter=False,
                                 name="elastic" if self.name is None
                                 else f"elastic.{self.name}",
                                 recorder_fn=self._rec)
        # hang-abort escalation: None = off (see module docstring)
        self.hang_abort_grace = None if hang_abort_grace is None \
            else float(hang_abort_grace)
        self.watchdog = watchdog
        self.flight_dir = flight_dir
        self.state = "idle"
        self.restarts = 0
        self.trainer = None
        # one TraceContext per run(): every state transition, trainer
        # step, and checkpoint write records under its trace id.  Pre-
        # set it to adopt an external trace; run() mints a root if None
        self.trace_ctx: Optional[TraceContext] = None
        self._state_span = None         # open span of the CURRENT state
        self._stop = False
        self._preemption = None
        self._loop_ident: Optional[int] = None
        self._in_segment = False

    # ------------------------------------------------------------------ #
    def _rec(self):
        if self._recorder is not None:
            return self._recorder
        from ..observability import null_recorder
        return null_recorder()

    def _capacity(self) -> list:
        import jax
        cap = self.capacity_fn() if self.capacity_fn is not None \
            else jax.devices()
        if isinstance(cap, int):
            cap = jax.devices()[:cap]
        return list(cap)

    def _event(self, kind: str, **fields):
        rec = self._rec()
        rec.inc(f"elastic/{kind}s" if not kind.endswith("s")
                else f"elastic/{kind}")
        rec.inc(f"health/elastic_{kind}")
        rec.emit_record("elastic_event", kind=kind, state=self.state,
                        job=self.name, **fields)
        rec.emit_record("health_event", condition=f"elastic_{kind}",
                        step=fields.get("step"), metric="elastic/devices",
                        value=fields.get("devices"), threshold=None,
                        action="elastic")
        if self.trace_ctx is not None:
            links = []
            if kind in ("shrink", "regrow", "displace", "preemption"):
                # the autoscaler/pool noted the decision context that
                # moved this job's devices; the transition event links
                # BACK to it — "this shrink was caused by that decision"
                cause = trace_spine.take_actuation(self.name or "train")
                if cause is not None:
                    links.append((cause.trace_id, cause.span_id,
                                  "caused_by"))
            trace_spine.get_tracer().event(
                f"elastic.{kind}", self.trace_ctx, subsystem="elastic",
                links=links, **fields)

    def _set_state(self, state: str):
        self.state = state
        rec = self._rec()
        rec.gauge("elastic/state_" + state, time.time())
        led = rec.get_ledger()
        if led is not None:
            # the goodput ledger's background phase follows the state
            # machine: draining -> preemption_drain, planning/resuming
            # -> preemption_replan, running/idle -> idle (steps fold
            # their own interval; only inter-step gaps land there)
            from ..observability.goodput import STATE_BUCKETS
            try:
                led.declare(STATE_BUCKETS.get(state, "idle"))
            except Exception:
                pass    # attribution must never block a transition
        if self.trace_ctx is not None:
            # contiguous state spans on the run's trace: the previous
            # state ends exactly where the next begins, so the merged
            # timeline (and critical-path attribution) has no gap
            # between drain, replan, and resume.  Only the run() loop
            # thread transitions state, so no lock is needed here.
            if self._state_span is not None:
                self._state_span.end()
            self._state_span = trace_spine.get_tracer().begin(
                f"elastic.{state}", self.trace_ctx, subsystem="elastic")

    def stop(self):
        """Ask run() to commit a checkpoint and return at the next
        step boundary (callable from any thread)."""
        self._stop = True

    # -- hang-abort ---------------------------------------------------- #
    def _setup_watchdog(self):
        """Build (or adopt) the stall watchdog and arm its hang-abort
        escalation against this supervisor's step loop."""
        if self.hang_abort_grace is None:
            return None
        wd = self.watchdog
        if wd is None:
            from ..observability.health import StallWatchdog
            wd = StallWatchdog(self._rec(), poll_interval=0.1)
            self.watchdog = wd
        flight = None
        if self.flight_dir is not None:
            from ..observability.health import FlightRecorder
            flight = FlightRecorder(self._rec(), self.flight_dir)
        wd.set_escalation(self.hang_abort_grace,
                          self._abort_wedged_step, flight=flight)
        return wd

    def _abort_wedged_step(self):
        """Watchdog escalation callback (runs on the poll thread):
        asynchronously raise HangAbortError in the step-loop thread so
        the wedged segment fails into the normal replan path.  Outside
        a running segment (the wedge resolved itself between the
        verdict and this call) it only logs — the raise would land in
        teardown/commit code that is making progress."""
        ident = self._loop_ident
        if not self._in_segment or ident is None:
            print("[elastic] hang-abort requested outside a running "
                  "segment; ignored", flush=True)
            return
        self._rec().inc("elastic/hang_aborts")
        print("[elastic] hang-abort: raising HangAbortError in the "
              "step loop — the wedged segment becomes a replan-and-"
              "resume", flush=True)
        if not _async_raise(ident, HangAbortError):
            print("[elastic] hang-abort: could not signal the "
                  "step-loop thread (already gone?)", flush=True)

    # ------------------------------------------------------------------ #
    def _build(self, axes, devices):
        from ..parallel import mesh as mesh_lib
        mesh = mesh_lib.create_mesh(dict(axes),
                                    plan_devices(axes, devices))
        trainer = self.trainer_factory(mesh)
        if self._recorder is not None and trainer._recorder is None:
            # one recorder across every segment: the trainer's
            # elastic/reshard + checkpoint counters land in the same
            # ring the supervisor's events do (set BEFORE init() — the
            # health variant changes the compiled step)
            trainer.set_telemetry(self._recorder)
        trainer.set_checkpoint(self.ckpt_dir, every_steps=self.ckpt_every,
                               keep=self.keep, layout="manifest",
                               shard_arrays=self.shard_arrays)
        if self.trace_ctx is not None \
                and hasattr(trainer, "set_trace_context"):
            # same trace id for the whole run: trainer steps and the
            # async checkpoint writes record as children of it
            trainer.set_trace_context(self.trace_ctx)
        trainer.init()
        try:
            trainer.load_checkpoint(self.ckpt_dir)
            resumed = True
        except FileNotFoundError:
            resumed = False     # fresh run: nothing to restore yet
        return trainer, resumed

    def _teardown(self, trainer):
        try:
            if trainer._ckpt_mgr is not None:
                trainer._ckpt_mgr.wait()
        finally:
            trainer.detach()

    def run(self, batch_fn: Optional[Callable] = None,
            steps: int = 100) -> list:
        """Train to ``steps`` total steps across however many meshes it
        takes; returns the per-step losses (recomputed steps — the tail
        a failure rolled back — keep their latest value).

        Capacity is read ONLY at planning points — the loop top and
        the ``replan_every`` polls.  A capacity change landing between
        them (a regrow arriving while a shrink's drain/commit is in
        flight — the autoscaler returning borrowed devices) is
        deferred to the next planning cycle, never interleaved with
        the transition in progress (regression:
        ``test_regrow_mid_drain_defers_to_next_planning_cycle``)."""
        batch_fn = batch_fn or self.batch_fn
        if batch_fn is None:
            raise ValueError("no batch_fn: pass one here or at init")
        self._stop = False      # re-arm: a stop()ped supervisor can run again
        if self.trace_ctx is None:
            self.trace_ctx = TraceContext.new_root()
        rec = self._rec()
        if self.handle_sigterm:
            from ..checkpoint import PreemptionHandler
            if self._preemption is None:
                self._preemption = PreemptionHandler()
            self._preemption.install()
        handler = self._preemption
        self._loop_ident = threading.get_ident()
        wd = self._setup_watchdog()
        losses: Dict[int, Any] = {}     # device scalars until segment drain
        prev_axes = None
        prev_used = None                # the device list the plan ran on
        first_step = None
        try:
            while True:
                try:
                    self._set_state("planning")
                    devices = self._capacity()
                    axes = plan_mesh(len(devices), self.template,
                                     self.min_axes, self.axis_costs)
                    used = plan_devices(axes, devices)
                    rec.gauge("elastic/devices", _prod(axes))
                    for name, size in axes.items():
                        rec.gauge(f"elastic/axis_{name}", size)
                    self._set_state("resuming")
                    try:
                        trainer, resumed = self._build(axes, devices)
                    except Exception:
                        if not self._backoff("build"):
                            raise
                        continue
                    if prev_axes is not None and axes != prev_axes:
                        # emitted only AFTER a successful build: a failed
                        # build's plan is a mesh the job never ran on, and
                        # must not show up as a topology transition
                        kind = "shrink" if _prod(axes) < _prod(prev_axes) \
                            else "regrow"
                        self._event(kind, from_axes=prev_axes, to_axes=axes,
                                    devices=_prod(axes))
                        print(f"[elastic] {kind}: {prev_axes} -> {axes}",
                              flush=True)
                    elif prev_used is not None and used != prev_used:
                        # same mesh shape on a DIFFERENT device subset: a
                        # fleet displacement (the pool handed these
                        # devices to another job).  Same-math relayout —
                        # the resumed curve is bit-identical — but it is
                        # a placement transition operators must see
                        self._event("displace", axes=axes,
                                    devices=_prod(axes))
                        print(f"[elastic] displace: {axes} moved to a new "
                              "device subset", flush=True)
                    prev_axes = axes
                    prev_used = used
                    self.trainer = trainer
                    if resumed:
                        self._event("resume", step=trainer._step_count,
                                    devices=_prod(axes), axes=axes)
                    start = trainer._step_count
                    if first_step is None:
                        first_step = start
                    outcome, fail = "completed", None
                    self._set_state("running")
                    if wd is not None:
                        # armed only while the step loop runs: a long
                        # rebuild/restore between segments must not read
                        # as a wedged step and re-escalate
                        wd.start()
                    self._in_segment = True
                    try:
                        for s in range(start, steps):
                            if self._stop:
                                outcome = "stopped"
                                break
                            if handler is not None and handler.requested:
                                outcome = "preempted"
                                break
                            if (self.replan_every and s > start
                                    and (s - start) % self.replan_every == 0):
                                new_devices = self._capacity()
                                new_axes = plan_mesh(len(new_devices),
                                                     self.template,
                                                     self.min_axes,
                                                     self.axis_costs)
                                # a device-SET change at equal size is a
                                # displacement (the pool reassigned us):
                                # this mesh's devices now belong to
                                # another job, so drain and rebuild on
                                # the new subset just like a resize
                                if (new_axes != axes
                                        or plan_devices(new_axes,
                                                        new_devices) != used):
                                    outcome = "replan"
                                    break
                            tokens, targets = batch_fn(s)
                            # the step.dispatch fault site: a delay: here
                            # models the wedge class the hang-abort exists
                            # for (and IS how the chaos matrix proves a
                            # wedged step ends in a replan, not a page)
                            faultplane.inject("step.dispatch", rec)
                            # device scalar, no float(): a per-step host
                            # sync would serialize dispatch against
                            # execution (GL002) — the floats are only
                            # needed at segment boundaries, and the bulk
                            # sync below runs before the mesh is torn down
                            if wd is not None and s == start:
                                # every segment's first step compiles
                                # (fresh trainer, possibly a new mesh) —
                                # minutes of legitimate XLA work that
                                # must not be read as a wedge and
                                # hang-aborted into a replan loop.
                                # Steps 2..N run under the full verdict
                                with wd.suspended():
                                    losses[s] = trainer.step(tokens,
                                                             targets)
                            else:
                                losses[s] = trainer.step(tokens, targets)
                            rec.gauge("elastic/steps_done", s + 1)
                            if (self.ckpt_every
                                    and (s + 1) % self.ckpt_every == 0
                                    and s + 1 < steps):
                                trainer.save_checkpoint(self.ckpt_dir)
                        # one bulk device→host sync per SEGMENT (GL002):
                        # the scalars must materialize before this mesh is
                        # torn down — and inside the try, so a device lost
                        # mid-drain is retried/replanned like any other
                        # segment failure, not a supervisor death
                        self._drain_losses(losses, strict=True)
                    except Exception as e:      # noqa: BLE001 — retried
                        # HangAbortError lands here too: a wedged step IS
                        # a failed segment — teardown, backoff, replan
                        outcome, fail = "failed", e
                        # best effort on the failure path: keep what still
                        # materializes, drop dead-mesh scalars (the resume
                        # recomputes everything past the last checkpoint)
                        self._drain_losses(losses, strict=False)
                    finally:
                        self._in_segment = False
                        if wd is not None:
                            wd.stop()
                    self._set_state("draining")
                    if outcome == "failed":
                        self._teardown(self.trainer)
                        self.trainer = None
                        if not self._backoff("segment", fail):
                            raise fail
                        continue
                    # clean outcomes commit a final synchronous checkpoint:
                    # nothing after this point can lose a completed step.
                    # A zero-new-step resumed segment skips it — its state
                    # is bit-identical to the checkpoint just restored, and
                    # rewriting every shard would stall shutdown for a full
                    # write for zero progress
                    tag = f"preempt_step_{trainer._step_count}" \
                        if outcome == "preempted" else None
                    if trainer._step_count > start or not resumed:
                        trainer.save_checkpoint(self.ckpt_dir, sync=True,
                                                tag=tag)
                    self._teardown(trainer)
                    self.trainer = None
                    self.restarts = 0           # a committed segment resets
                    if outcome == "preempted":
                        self._event("preemption", step=trainer._step_count,
                                    devices=_prod(axes))
                        print(f"[elastic] preempted at step "
                              f"{trainer._step_count}; final checkpoint "
                              "committed, re-planning from surviving "
                              "capacity", flush=True)
                        handler.reset()
                        continue
                    if outcome == "replan":
                        continue
                    self._set_state("idle")
                    # `in losses`: a failed segment may have dropped dead-
                    # mesh scalars that no later resume recomputed (steps
                    # before its own mid-segment checkpoint)
                    return [losses[s]
                            for s in range(first_step, max(losses) + 1)
                            if s in losses] \
                        if losses else []
                except HangAbortError as e:
                    # the async abort can land AFTER the step loop's
                    # finally — the wedge released in the tiny window
                    # between the verdict and the raise, so the
                    # exception hit drain/commit/teardown code instead.
                    # Wherever in the segment body it lands, it is ONE
                    # segment failure, never a supervisor death.  A
                    # deliberate re-raise after an exhausted backoff
                    # passes straight through.
                    if self.restarts > self.max_restarts:
                        raise
                    # mirror the inner failure path: materialize what
                    # still lives BEFORE the mesh is torn down — an
                    # abort that interrupted the inner drain would
                    # otherwise leave device scalars whose buffers die
                    # with the teardown in the final return value
                    self._drain_losses(losses, strict=False)
                    stale = self.trainer
                    if stale is not None:
                        try:
                            self._teardown(stale)
                        except Exception:
                            pass
                        self.trainer = None
                    if not self._backoff("hang_abort", e):
                        raise
                    continue
        finally:
            if self._state_span is not None:
                self._state_span.end()
                self._state_span = None
            if self.handle_sigterm and handler is not None:
                handler.uninstall()

    @staticmethod
    def _drain_losses(losses: Dict[int, Any], strict: bool):
        """Materialize the segment's device scalars to floats in place.
        ``strict=False`` (the segment-failure path) drops entries whose
        buffers died with the mesh instead of raising — those steps are
        recomputed past the restored checkpoint anyway."""
        for k, v in list(losses.items()):
            if isinstance(v, float):
                continue
            try:
                losses[k] = float(v)
            except Exception:
                if strict:
                    raise
                losses.pop(k)
        return losses

    def _backoff(self, what: str, exc: Exception = None) -> bool:
        """Count a failure; sleep per the unified RetryPolicy schedule
        (jitter off — identical to the legacy exponential); False when
        retries are exhausted (caller re-raises)."""
        self.restarts += 1
        self._event("failure", attempt=self.restarts, what=what,
                    error=None if exc is None else repr(exc))
        if self.restarts > self.max_restarts:
            self.retry.count_giveup()
            return False
        delay = self.retry.delay_for(self.restarts)
        self.retry.count_attempt()
        print(f"[elastic] {what} failed ({exc!r}); retry "
              f"{self.restarts}/{self.max_restarts} in {delay:.1f}s",
              flush=True)
        time.sleep(delay)
        return True
