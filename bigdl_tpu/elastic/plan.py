"""Mesh re-planning: fit a named-axis topology onto surviving capacity.

The supervisor keeps one *template* mesh ({axis: size} at full
capacity) and asks :func:`plan_mesh` what to run on whatever devices
are still alive.  Axis names and order never change — every parameter
PartitionSpec stays valid — and each axis size must be a **divisor of
its template size**, so the model-divisibility constraints that held
at full capacity (head counts, d_model multiples, global-batch
splits) survive every shrink.

Within those constraints the planner returns the **largest feasible
mesh**: it searches the (small) divisor lattice exhaustively instead
of walking one prime-factor chain — {dp: 6, tp: 4} on 8 surviving
devices yields {dp: 2, tp: 4} (all 8 used), not the {dp: 1, tp: 4} a
divide-by-smallest-prime greedy would strand itself at.  Ties on
device count keep late-priority axes (tp, pp, sp) at full size and
shrink ``dp`` first: a smaller data-parallel degree is pure same-math
re-batching, while tp/sp sizes are entangled with model dimensions.

Regrow is the same call with more devices: the plan monotonically
approaches the template as capacity returns, and never exceeds it.
"""
from __future__ import annotations

import itertools
from typing import Dict, Optional, Sequence

import numpy as np

# shrink order: batch-ish axes first, model-entangled axes last
SHRINK_PRIORITY: Sequence[str] = ("dp", "fsdp", "sp", "pp", "tp")


def _prod(axes: Dict[str, int]) -> int:
    return int(np.prod(list(axes.values()), dtype=np.int64)) if axes else 1


def _divisors(n: int):
    return [d for d in range(1, n + 1) if n % d == 0]


def _axis_candidates(axes: Dict[str, int],
                     floors: Dict[str, int]) -> Dict[str, list]:
    """Per-axis legal sizes: divisors of the template size that meet the
    axis's floor.  ONE definition of what a legal axis size is — the
    planner's search space and fleet admission's floor reservation must
    never disagree.  Raises ``ValueError`` when an axis has none."""
    out = {}
    for k, v in axes.items():
        floor = max(1, floors.get(k, 1))
        cands = [d for d in _divisors(v) if d >= floor]
        if not cands:
            raise ValueError(
                f"axis {k!r}: no divisor of {v} meets its floor {floor}")
        out[k] = cands
    return out


def plan_mesh(n_devices: int, template: Dict[str, int],
              min_axes: Optional[Dict[str, int]] = None) -> Dict[str, int]:
    """Largest mesh ≤ ``template`` (axis-wise, divisor-constrained)
    fitting ``n_devices``.

    ``min_axes`` pins lower bounds (e.g. ``{"tp": 2}`` when a layer's
    sharded dimension cannot be replicated); a shrink that would land
    below a pin is illegal, never silently applied.  Raises
    ``ValueError`` when no divisor combination fits — the caller
    decides whether that is fatal or worth waiting out.
    """
    if n_devices < 1:
        raise ValueError(f"no surviving capacity (n_devices={n_devices})")
    axes = {str(k): int(v) for k, v in template.items()}
    for k, v in axes.items():
        if v < 1:
            raise ValueError(f"template axis {k!r} has size {v}")
    floors = {str(k): int(v) for k, v in (min_axes or {}).items()}
    names = list(axes)
    cand_map = _axis_candidates(axes, floors)
    cand_lists = [cand_map[k] for k in names]
    # preference on ties: keep LATE-priority axes (tp, pp, sp) at full
    # size, shrink dp first — compare sizes in reverse priority order
    rank = {a: i for i, a in enumerate(SHRINK_PRIORITY)}
    order = sorted(range(len(names)),
                   key=lambda i: -rank.get(names[i], len(SHRINK_PRIORITY)))
    best = None
    for combo in itertools.product(*cand_lists):
        p = int(np.prod(combo, dtype=np.int64))
        if p > n_devices:
            continue
        key = (p, tuple(combo[i] for i in order))
        if best is None or key > best[0]:
            best = (key, combo)
    if best is None:
        raise ValueError(
            f"cannot shrink mesh {dict(template)} onto {n_devices} "
            f"device(s) with floors {floors}")
    return dict(zip(names, best[1]))


def plan_devices(axes: Dict[str, int], devices) -> list:
    """The device prefix a plan actually uses (stable ordering keeps
    reshard layouts deterministic across replans)."""
    need = _prod(axes)
    devices = list(devices)
    if need > len(devices):
        raise ValueError(f"plan {axes} needs {need} devices, "
                         f"have {len(devices)}")
    return devices[:need]
