"""Mesh re-planning: fit a named-axis topology onto surviving capacity.

The supervisor keeps one *template* mesh ({axis: size} at full
capacity) and asks :func:`plan_mesh` what to run on whatever devices
are still alive.  Axis names and order never change — every parameter
PartitionSpec stays valid — and each axis size must be a **divisor of
its template size**, so the model-divisibility constraints that held
at full capacity (head counts, d_model multiples, global-batch
splits) survive every shrink.

Within those constraints the planner returns the **largest feasible
mesh**: it searches the (small) divisor lattice exhaustively instead
of walking one prime-factor chain — {dp: 6, tp: 4} on 8 surviving
devices yields {dp: 2, tp: 4} (all 8 used), not the {dp: 1, tp: 4} a
divide-by-smallest-prime greedy would strand itself at.  Ties on
device count break by **per-axis shrink cost** (``AXIS_SHRINK_COST``,
overridable per call): shrinking ``dp``/``fsdp`` is pure same-math
re-batching of replicated state (a cheap re-layout at resume), while
``pp``/``tp``/``ep`` shrinks re-partition tensors/stages/experts —
expensive restores and, for tp, dimensions entangled with the model.
A preempted 4-axis job therefore shrinks the **cheapest viable axis**:
dp4×tp2 on 4 surviving devices resumes as dp2×tp2, never dp4×tp1.

Regrow is the same call with more devices: the plan monotonically
approaches the template as capacity returns, and never exceeds it.
"""
from __future__ import annotations

import itertools
import math
from typing import Dict, Optional, Sequence

import numpy as np

# shrink order: batch-ish axes first, model-entangled axes last
# (kept as the deterministic last-resort tie-break under custom costs)
SHRINK_PRIORITY: Sequence[str] = ("dp", "fsdp", "sp", "pp", "tp", "ep")

# relative cost of HALVING an axis (per log2 shrink step).  dp/fsdp
# re-layouts are cheap (replicated/1-D-resharded state, bit-exact or
# documented-ulp resumes — docs/checkpointing.md taxonomy); pp/ep move
# whole stages/experts; tp re-partitions every sharded tensor AND its
# size is entangled with model dims (head counts, d_ff multiples).
AXIS_SHRINK_COST: Dict[str, float] = {
    "dp": 1.0, "fsdp": 2.0, "sp": 4.0, "pp": 8.0, "ep": 8.0, "tp": 16.0}


def shrink_cost(template: Dict[str, int], plan: Dict[str, int],
                axis_costs: Optional[Dict[str, float]] = None) -> float:
    """Total cost of shrinking ``template`` to ``plan``:
    ``sum(cost[axis] * log2(template/plan))`` — log2 because each
    halving is one re-layout of the axis's state, and costs compose
    multiplicatively along the divisor chain."""
    costs = dict(AXIS_SHRINK_COST)
    costs.update(axis_costs or {})
    total = 0.0
    for k, v in template.items():
        s = plan.get(k, 1)
        if s < v:
            total += costs.get(k, max(costs.values())) \
                * math.log2(v / s)
    return total


def _prod(axes: Dict[str, int]) -> int:
    return int(np.prod(list(axes.values()), dtype=np.int64)) if axes else 1


def _divisors(n: int):
    return [d for d in range(1, n + 1) if n % d == 0]


def _axis_candidates(axes: Dict[str, int],
                     floors: Dict[str, int]) -> Dict[str, list]:
    """Per-axis legal sizes: divisors of the template size that meet the
    axis's floor.  ONE definition of what a legal axis size is — the
    planner's search space and fleet admission's floor reservation must
    never disagree.  Raises ``ValueError`` when an axis has none."""
    out = {}
    for k, v in axes.items():
        floor = max(1, floors.get(k, 1))
        cands = [d for d in _divisors(v) if d >= floor]
        if not cands:
            raise ValueError(
                f"axis {k!r}: no divisor of {v} meets its floor {floor}")
        out[k] = cands
    return out


def plan_mesh(n_devices: int, template: Dict[str, int],
              min_axes: Optional[Dict[str, int]] = None,
              axis_costs: Optional[Dict[str, float]] = None
              ) -> Dict[str, int]:
    """Largest mesh ≤ ``template`` (axis-wise, divisor-constrained)
    fitting ``n_devices``; device-count ties break by MINIMUM total
    shrink cost (:func:`shrink_cost`), so the plan shrinks the
    cheapest viable axis — dp before fsdp before sp/pp/ep before tp
    under the default ``AXIS_SHRINK_COST``, or whatever ``axis_costs``
    overrides say (a job whose tp re-layout is cheap on its model can
    invert the preference without forking the planner).

    ``min_axes`` pins lower bounds (e.g. ``{"tp": 2}`` when a layer's
    sharded dimension cannot be replicated); a shrink that would land
    below a pin is illegal, never silently applied.  Raises
    ``ValueError`` when no divisor combination fits — the caller
    decides whether that is fatal or worth waiting out.
    """
    if n_devices < 1:
        raise ValueError(f"no surviving capacity (n_devices={n_devices})")
    axes = {str(k): int(v) for k, v in template.items()}
    for k, v in axes.items():
        if v < 1:
            raise ValueError(f"template axis {k!r} has size {v}")
    floors = {str(k): int(v) for k, v in (min_axes or {}).items()}
    names = list(axes)
    cand_map = _axis_candidates(axes, floors)
    cand_lists = [cand_map[k] for k in names]
    # deterministic last-resort tie-break (equal device count AND equal
    # cost, e.g. under a flat custom cost map): keep LATE-priority axes
    # at full size — compare sizes in reverse priority order
    rank = {a: i for i, a in enumerate(SHRINK_PRIORITY)}
    order = sorted(range(len(names)),
                   key=lambda i: -rank.get(names[i], len(SHRINK_PRIORITY)))
    best = None
    for combo in itertools.product(*cand_lists):
        p = int(np.prod(combo, dtype=np.int64))
        if p > n_devices:
            continue
        plan = dict(zip(names, combo))
        key = (p, -shrink_cost(axes, plan, axis_costs),
               tuple(combo[i] for i in order))
        if best is None or key > best[0]:
            best = (key, combo)
    if best is None:
        raise ValueError(
            f"cannot shrink mesh {dict(template)} onto {n_devices} "
            f"device(s) with floors {floors}")
    return dict(zip(names, best[1]))


def plan_devices(axes: Dict[str, int], devices) -> list:
    """The device prefix a plan actually uses (stable ordering keeps
    reshard layouts deterministic across replans)."""
    need = _prod(axes)
    devices = list(devices)
    if need > len(devices):
        raise ValueError(f"plan {axes} needs {need} devices, "
                         f"have {len(devices)}")
    return devices[:need]
