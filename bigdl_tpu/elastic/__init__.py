"""bigdl_tpu.elastic — survive preemption by shrinking, not dying.

The "seamless scaling of AI pipelines" story of BigDL 2.0
(arXiv:2204.01715) made TPU-native: v2 manifest checkpoints record the
save-time mesh and restore reassembles global arrays from whatever
slice shards exist (:mod:`bigdl_tpu.checkpoint.reshard`), so the
:class:`ElasticSupervisor` can commit a final checkpoint on SIGTERM,
re-plan the largest mesh the surviving capacity supports
(:func:`plan_mesh`, shrinking ``dp`` first), resume through the
reshard path, and regrow when devices return — emitting ``elastic/*``
counters and health events through the existing Recorder.

See ``docs/checkpointing.md`` § Elastic resume.
"""
from __future__ import annotations

from .plan import SHRINK_PRIORITY, plan_devices, plan_mesh
from .supervisor import ElasticSupervisor

__all__ = ["ElasticSupervisor", "plan_mesh", "plan_devices",
           "SHRINK_PRIORITY"]
